"""PixelsService: image id -> PixelSource (≙ ``ome.io.nio.PixelsService``,
consumed at ``ImageRegionRequestHandler.java:302-309``).

The reference resolves an image through the OMERO DB + binary repository;
here a data directory holds one chunked pyramid per image
(``<data_dir>/<image_id>/meta.json``), mirroring the reference's
``omero.data.dir`` layout role (``config.yaml:19-20``).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional

from .pixelsource import PixelSource
from .store import ChunkedPyramidStore

DEFAULT_MAX_OPEN = 128


class PixelsService:
    """Opens pixel sources from a data directory, with a bounded LRU handle
    cache (each open store holds live memmaps, so the bound caps fds and
    address space on long-running servers)."""

    def __init__(self, data_dir: str, max_open: int = DEFAULT_MAX_OPEN):
        self.data_dir = data_dir
        self.max_open = max_open
        self._lock = threading.Lock()
        self._open: "OrderedDict[int, ChunkedPyramidStore]" = OrderedDict()

    def image_dir(self, image_id: int) -> str:
        return os.path.join(self.data_dir, str(image_id))

    def exists(self, image_id: int) -> bool:
        return os.path.exists(os.path.join(self.image_dir(image_id),
                                           "meta.json"))

    def get_pixel_source(self, image_id: int) -> PixelSource:
        """≙ ``PixelsService.getPixelBuffer(pixels, false)``."""
        with self._lock:
            src = self._open.get(image_id)
            if src is not None:
                self._open.move_to_end(image_id)
                return src
        if not self.exists(image_id):
            raise FileNotFoundError(
                f"no pixel data for image {image_id} under "
                f"{self.data_dir}"
            )
        src = ChunkedPyramidStore(self.image_dir(image_id))
        with self._lock:
            # Double-check: a concurrent opener may have won the race;
            # keep theirs and drop ours so no store leaks its memmaps.
            existing = self._open.get(image_id)
            if existing is not None:
                self._open.move_to_end(image_id)
                src.close()
                return existing
            self._open[image_id] = src
            while len(self._open) > self.max_open:
                _, evicted = self._open.popitem(last=False)
                evicted.close()
        return src

    def close(self) -> None:
        with self._lock:
            for src in self._open.values():
                src.close()
            self._open.clear()
