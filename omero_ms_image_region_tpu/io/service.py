"""PixelsService: image id -> PixelSource (≙ ``ome.io.nio.PixelsService``,
consumed at ``ImageRegionRequestHandler.java:302-309``).

The reference resolves an image through the OMERO DB + binary repository;
here a data directory holds one chunked pyramid per image
(``<data_dir>/<image_id>/meta.json``), mirroring the reference's
``omero.data.dir`` layout role (``config.yaml:19-20``).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Optional

from .ometiff import OmeTiffSource, find_tiff
from .pixelsource import PixelSource
from .store import ChunkedPyramidStore

DEFAULT_MAX_OPEN = 128


class PixelsService:
    """Opens pixel sources from a data directory, with a bounded LRU handle
    cache (each open store holds live memmaps, so the bound caps fds and
    address space on long-running servers).

    Backend is sniffed per image directory: a ``meta.json`` selects the
    chunked pyramid store; otherwise an ``*.ome.tif(f)`` / ``*.tif(f)``
    file selects the OME-TIFF reader — the role Bio-Formats format
    dispatch plays behind ``PixelsService.getPixelBuffer``
    (``ImageRegionRequestHandler.java:302-309``)."""

    def __init__(self, data_dir: str, max_open: int = DEFAULT_MAX_OPEN):
        self.data_dir = data_dir
        self.max_open = max_open
        self._lock = threading.Lock()
        self._open: "OrderedDict[int, PixelSource]" = OrderedDict()

    def image_dir(self, image_id: int) -> str:
        return os.path.join(self.data_dir, str(image_id))

    def _sniff(self, image_id: int) -> Optional[str]:
        """"chunked" | path-to-tiff | None."""
        d = self.image_dir(image_id)
        if os.path.exists(os.path.join(d, "meta.json")):
            return "chunked"
        return find_tiff(d)

    def exists(self, image_id: int) -> bool:
        return self._sniff(image_id) is not None

    def get_pixel_source(self, image_id: int) -> PixelSource:
        """≙ ``PixelsService.getPixelBuffer(pixels, false)``."""
        with self._lock:
            src = self._open.get(image_id)
            if src is not None:
                self._open.move_to_end(image_id)
                return src
        backend = self._sniff(image_id)
        if backend is None:
            raise FileNotFoundError(
                f"no pixel data for image {image_id} under "
                f"{self.data_dir}"
            )
        if backend == "chunked":
            src = ChunkedPyramidStore(self.image_dir(image_id))
        else:
            src = OmeTiffSource(backend)
        with self._lock:
            # Double-check: a concurrent opener may have won the race;
            # keep theirs and drop ours so no store leaks its memmaps.
            existing = self._open.get(image_id)
            if existing is not None:
                self._open.move_to_end(image_id)
                src.close()
                return existing
            self._open[image_id] = src
            while len(self._open) > self.max_open:
                # Drop WITHOUT close(): a concurrent request may still be
                # mid-read on the evicted source (close would yank the
                # TIFF file handle out from under it).  The last live
                # reference releases the handle via the source's
                # finalizer; memmap-backed stores release on GC anyway.
                self._open.popitem(last=False)
        return src

    def close(self) -> None:
        with self._lock:
            for src in self._open.values():
                src.close()
            self._open.clear()
