"""PixelsService: image id -> PixelSource (≙ ``ome.io.nio.PixelsService``,
consumed at ``ImageRegionRequestHandler.java:302-309``).

The reference resolves an image through the OMERO DB + binary repository;
here a data directory holds one chunked pyramid per image
(``<data_dir>/<image_id>/meta.json``), mirroring the reference's
``omero.data.dir`` layout role (``config.yaml:19-20``).
"""

from __future__ import annotations

import gc
import os
import sys
import threading
from collections import OrderedDict
from typing import List, Optional

from .ngff import NgffZarrSource, find_ngff
from .ometiff import OmeTiffSource, find_tiff
from .pixelsource import PixelSource
from .store import ChunkedPyramidStore

DEFAULT_MAX_OPEN = 128


class PixelsService:
    """Opens pixel sources from a data directory, with a bounded LRU handle
    cache (each open store holds live memmaps, so the bound caps fds and
    address space on long-running servers).

    Backend is sniffed per image directory: a ``meta.json`` selects the
    chunked pyramid store; ``.zattrs``/``.zarray`` markers (directly or
    in a ``*.zarr`` child) select the OME-NGFF reader; otherwise an
    ``*.ome.tif(f)`` / ``*.tif(f)`` file selects the OME-TIFF reader —
    the role Bio-Formats format dispatch plays behind
    ``PixelsService.getPixelBuffer``
    (``ImageRegionRequestHandler.java:302-309``)."""

    # Evicted-set size past which a gc.collect() is forced: a reference
    # cycle (e.g. a captured exception traceback) can keep an evicted
    # source's refcount high until a cycle collection runs.
    _GC_THRESHOLD = 8

    def __init__(self, data_dir: str, max_open: int = DEFAULT_MAX_OPEN,
                 repo_root: Optional[str] = None):
        self.data_dir = data_dir
        self.max_open = max_open
        # OMERO binary-repository mount (``omero.data.dir``,
        # ``config.yaml:19-20``): when set, images absent from the
        # per-image data_dir layout resolve through DB-provided
        # repo-relative paths (ManagedRepository filesets, legacy
        # Pixels/<id> ROMIO files) with zero re-arrangement — the role
        # of the reference's file-path resolver bean
        # (``beanRefContext.xml:13-16``).
        self.repo_root = repo_root
        self._lock = threading.Lock()
        self._open: "OrderedDict[int, PixelSource]" = OrderedDict()
        # Sources dropped from the LRU while possibly still mid-read;
        # closed deterministically once no outside reference remains
        # (see _drain_evicted) so fds/memmaps cannot outgrow max_open
        # under heavy image churn.
        self._evicted: List[PixelSource] = []

    def _drain_evicted_locked(self) -> int:
        """Close evicted sources no longer referenced anywhere else;
        returns how many stragglers remain.

        Caller holds ``self._lock``.  Refcount 3 = the list slot, the
        loop variable, and getrefcount's argument — i.e. no reader still
        holds the source.
        """
        still: List[PixelSource] = []
        for src in self._evicted:
            if sys.getrefcount(src) <= 3:
                try:
                    src.close()
                except Exception:
                    pass
            else:
                still.append(src)
        self._evicted = still
        return len(still)

    def _gc_and_drain(self) -> None:
        """Straggler pressure relief: a reference cycle (e.g. a captured
        exception traceback) can pin an evicted source until a cycle
        collection runs.  The collection happens OUTSIDE the lock so
        concurrent lookups are never stalled behind a full gc pass."""
        gc.collect()
        with self._lock:
            self._drain_evicted_locked()

    def image_dir(self, image_id: int) -> str:
        return os.path.join(self.data_dir, str(image_id))

    def _sniff(self, image_id: int) -> Optional[tuple]:
        """("chunked"|"ngff"|"tiff", path) | None."""
        d = self.image_dir(image_id)
        if os.path.exists(os.path.join(d, "meta.json")):
            return ("chunked", d)
        ngff = find_ngff(d)
        if ngff is not None:
            return ("ngff", ngff)
        tiff = find_tiff(d)
        if tiff is not None:
            return ("tiff", tiff)
        return None

    def exists(self, image_id: int) -> bool:
        return self._sniff(image_id) is not None

    def is_open(self, image_id: int) -> bool:
        """LRU probe without disk or DB I/O: a repo-resolved image that
        is already open needs no re-resolution on the hot tile path."""
        with self._lock:
            return image_id in self._open

    def get_open_source(self, image_id: int) -> Optional[PixelSource]:
        """The already-open source, or None — NEVER sniffs or opens,
        so it is safe to call on an event loop (the serving fast path;
        a concurrent eviction just returns None and the caller takes
        the off-loop open)."""
        with self._lock:
            src = self._open.get(image_id)
            if src is not None:
                self._open.move_to_end(image_id)
            return src

    def _open_from_repo(self, image_id: int, candidates, pixels):
        """Open the first usable repo-relative candidate path.

        TIFF-suffixed entries (``.ome.tif(f)`` preferred) open through
        the OME-TIFF reader; ``*.zarr`` directories open as OME-NGFF;
        a ``Pixels/<id>`` entry opens as a legacy ROMIO buffer, which
        needs the DB geometry (``pixels``).
        """
        from .romio import RomioPixelSource

        def rank(rel: str) -> int:
            low = rel.lower()
            if low.endswith((".ome.tif", ".ome.tiff")):
                return 0
            if low.endswith((".tif", ".tiff", ".svs", ".ndpi")):
                return 1       # TIFF-based vendor formats included
            return 2

        tried = []
        for rel in sorted(candidates, key=rank):
            path = os.path.join(self.repo_root, rel)
            if os.path.isdir(path):
                ngff = find_ngff(path)
                if ngff is not None:
                    return NgffZarrSource(ngff)
                tried.append(rel)
                continue
            if not os.path.isfile(path):
                tried.append(rel)
                continue
            if rank(rel) < 2:
                return OmeTiffSource(path)
            if rel.startswith("Pixels/"):
                if pixels is None:
                    raise ValueError(
                        f"image {image_id}: ROMIO path {rel} needs "
                        f"pixels geometry to open")
                return RomioPixelSource(path, pixels)
            # Unknown extension: vendor WSI files are very often plain
            # TIFF containers under another name — sniff the magic
            # rather than trusting the suffix.
            with open(path, "rb") as f:
                magic = f.read(4)
            if magic[:2] in (b"II", b"MM"):
                return OmeTiffSource(path)
            tried.append(rel)   # present but not a format we serve
        raise FileNotFoundError(
            f"image {image_id}: no usable pixel file under "
            f"{self.repo_root} (candidates: {tried or candidates})")

    def get_pixel_source(self, image_id: int, candidates=None,
                         pixels=None) -> PixelSource:
        """≙ ``PixelsService.getPixelBuffer(pixels, false)``.

        ``candidates`` are repo-root-relative paths from the metadata
        DB (``DbMetadataService.resolve_image_paths``); they apply only
        when the per-image ``data_dir`` layout has no entry, so a local
        override always wins.
        """
        with self._lock:
            src = self._open.get(image_id)
            if src is not None:
                self._open.move_to_end(image_id)
                if self._evicted:
                    # Steady-state hit traffic must still release
                    # finished readers' handles (no gc here: a plain
                    # refcount scan, trivial when the list is empty).
                    self._drain_evicted_locked()
                return src
        backend = self._sniff(image_id)
        if backend is None and candidates and self.repo_root:
            src = self._open_from_repo(image_id, candidates, pixels)
        elif backend is None:
            raise FileNotFoundError(
                f"no pixel data for image {image_id} under "
                f"{self.data_dir}"
            )
        elif backend[0] == "chunked":
            src = ChunkedPyramidStore(backend[1])
        elif backend[0] == "ngff":
            src = NgffZarrSource(backend[1])
        else:
            src = OmeTiffSource(backend[1])
        with self._lock:
            # Double-check: a concurrent opener may have won the race;
            # keep theirs and drop ours so no store leaks its memmaps.
            existing = self._open.get(image_id)
            if existing is not None:
                self._open.move_to_end(image_id)
                src.close()
                return existing
            self._open[image_id] = src
            while len(self._open) > self.max_open:
                # Do not close() here: a concurrent request may still be
                # mid-read on the evicted source (close would yank the
                # TIFF file handle out from under it).  Park it on the
                # deferred-close list instead; it is closed on a later
                # drain once its refcount shows no reader remains.
                self._evicted.append(self._open.popitem(last=False)[1])
            stragglers = self._drain_evicted_locked()
        if stragglers > self._GC_THRESHOLD:
            self._gc_and_drain()
        return src

    def invalidate(self, image_id: int) -> None:
        """Drop a cached open handle so the next request re-sniffs the
        image directory.  The pyramid job calls this after committing
        an NGFF group: the sniff order prefers it, but an LRU-resident
        pre-build source would otherwise keep serving unpyramided."""
        with self._lock:
            src = self._open.pop(image_id, None)
            if src is not None:
                # Deferred close — a concurrent reader may be mid-read.
                self._evicted.append(src)
            self._drain_evicted_locked()

    def close(self) -> None:
        with self._lock:
            for src in self._open.values():
                src.close()
            self._open.clear()
            for src in self._evicted:
                try:
                    src.close()
                except Exception:
                    pass
            self._evicted.clear()
