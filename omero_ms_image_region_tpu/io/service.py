"""PixelsService: image id -> PixelSource (≙ ``ome.io.nio.PixelsService``,
consumed at ``ImageRegionRequestHandler.java:302-309``).

The reference resolves an image through the OMERO DB + binary repository;
here a data directory holds one chunked pyramid per image
(``<data_dir>/<image_id>/meta.json``), mirroring the reference's
``omero.data.dir`` layout role (``config.yaml:19-20``).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from .pixelsource import PixelSource
from .store import ChunkedPyramidStore


class PixelsService:
    """Opens pixel sources from a data directory, with a handle cache."""

    def __init__(self, data_dir: str):
        self.data_dir = data_dir
        self._open: Dict[int, ChunkedPyramidStore] = {}

    def image_dir(self, image_id: int) -> str:
        return os.path.join(self.data_dir, str(image_id))

    def exists(self, image_id: int) -> bool:
        return os.path.exists(os.path.join(self.image_dir(image_id),
                                           "meta.json"))

    def get_pixel_source(self, image_id: int) -> PixelSource:
        """≙ ``PixelsService.getPixelBuffer(pixels, false)``."""
        src = self._open.get(image_id)
        if src is None:
            if not self.exists(image_id):
                raise FileNotFoundError(
                    f"no pixel data for image {image_id} under "
                    f"{self.data_dir}"
                )
            src = ChunkedPyramidStore(self.image_dir(image_id))
            self._open[image_id] = src
        return src

    def close(self) -> None:
        for src in self._open.values():
            src.close()
        self._open.clear()
