"""In-memory pixel source (≙ ``ome.io.nio.InMemoryPlanarPixelBuffer``,
consumed at ``ImageRegionRequestHandler.java:554-555`` to re-render projected
planes, and the natural fake backend for tests)."""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..server.region import RegionDef


class InMemoryPixelSource:
    """PixelSource over a [C, Z, H, W] (or [Z, C, H, W]-free) ndarray.

    Optionally carries a synthesized downsampled pyramid (mean-pool by 2)
    so pyramid logic is testable without disk.
    """

    def __init__(self, planes: np.ndarray, tile: Tuple[int, int] = (256, 256),
                 pyramid_levels: int = 1):
        if planes.ndim != 4:
            raise ValueError("planes must be [C, Z, H, W]")
        self._levels = [planes]
        for _ in range(1, pyramid_levels):
            prev = self._levels[-1]
            h, w = prev.shape[-2] // 2, prev.shape[-1] // 2
            if h < 1 or w < 1:
                break
            ds = prev[..., : h * 2, : w * 2].reshape(
                prev.shape[0], prev.shape[1], h, 2, w, 2
            ).astype(np.float64).mean(axis=(3, 5))
            if np.issubdtype(planes.dtype, np.integer):
                ds = np.round(ds)
            self._levels.append(ds.astype(planes.dtype))
        self._tile = tile
        self.closed = False

    @property
    def dtype(self) -> np.dtype:
        return self._levels[0].dtype

    def resolution_levels(self) -> int:
        return len(self._levels)

    def resolution_descriptions(self) -> List[Tuple[int, int]]:
        return [(lv.shape[-1], lv.shape[-2]) for lv in self._levels]

    def tile_size(self) -> Tuple[int, int]:
        return self._tile

    def get_region(self, z: int, c: int, t: int, region: RegionDef,
                   level: int = 0) -> np.ndarray:
        lv = self._levels[level]
        return np.array(
            lv[c, z, region.y:region.y + region.height,
               region.x:region.x + region.width]
        )

    def get_stack(self, c: int, t: int) -> np.ndarray:
        return np.array(self._levels[0][c])

    def close(self) -> None:
        self.closed = True
