"""Fixed-size mergeable streaming rank sketch.

The perf sentinel (``server.sentinel``) needs per-(route, shape)
latency quantiles that are (a) cheap enough to update on EVERY request
— the PR 6 overhead budget is <100µs/op for the whole forensics
plane, so the insert must be two list ops, no lock, no allocation —
(b) bounded in memory no matter how long the process lives, and
(c) mergeable across fleet members so the frontend can answer
``/debug/sentinel`` with ONE fleet-wide p99 instead of N
incomparable ones.

A geometric bucket ladder gives all three.  Values land in buckets
whose bounds grow by a fixed ratio (``10 ** (1 / buckets_per_decade)``)
— the classic HDR/DDSketch layout — so the ladder is a tuple computed
once per parameter set and shared by every sketch instance.  The
insert is the ``telemetry.Histogram.add`` idiom verbatim: one
``bisect_right`` into the shared bounds plus one GIL-atomic list-slot
increment.  Merging two sketches with the same ladder is element-wise
count addition, which is associative and commutative by construction
— the property the fleet merge (and its test) relies on.

Quantile answers carry bounded RELATIVE error: a value is reported as
the geometric midpoint of its bucket, so the worst case error is
``sqrt(ratio) - 1`` (~3.6% at the default 32 buckets/decade).  That is
plenty to call a 1.5x p99 drift and costs 2-3 orders of magnitude
less than exact order statistics.

No imports beyond stdlib; importable from bench, tests and the
sidecar without the server stack.
"""

from __future__ import annotations

from bisect import bisect_right
from typing import Dict, List, Optional, Tuple

__all__ = ["RankSketch"]

# Ladders are keyed by (lo, hi, buckets_per_decade) and shared:
# building one is O(decades * buckets) and every sketch with the same
# parameters must agree bucket-for-bucket or merging would be
# meaningless.
_LADDERS: Dict[Tuple[float, float, int], Tuple[float, ...]] = {}


def _ladder(lo: float, hi: float,
            buckets_per_decade: int) -> Tuple[float, ...]:
    key = (lo, hi, buckets_per_decade)
    ladder = _LADDERS.get(key)
    if ladder is None:
        ratio = 10.0 ** (1.0 / buckets_per_decade)
        bounds: List[float] = []
        b = lo
        while b < hi:
            bounds.append(b)
            b *= ratio
        bounds.append(hi)
        ladder = tuple(bounds)
        _LADDERS[key] = ladder
    return ladder


class RankSketch:
    """Streaming quantile sketch over a geometric bucket ladder.

    ``add`` is safe to call from any thread without a lock: the only
    shared mutation is a single list-slot increment (GIL-atomic, the
    ``Histogram.add`` idiom).  Everything else (quantile, merge,
    serialization) runs at tick/debug cadence where a racy read of a
    count that is one insert stale is invisible.
    """

    __slots__ = ("lo", "hi", "buckets_per_decade", "bounds", "counts")

    def __init__(self, lo: float = 0.01, hi: float = 1e6,
                 buckets_per_decade: int = 32):
        if not (0 < lo < hi):
            raise ValueError("need 0 < lo < hi")
        if buckets_per_decade < 1:
            raise ValueError("need buckets_per_decade >= 1")
        self.lo = float(lo)
        self.hi = float(hi)
        self.buckets_per_decade = int(buckets_per_decade)
        self.bounds = _ladder(self.lo, self.hi,
                              self.buckets_per_decade)
        # bucket i holds values in (bounds[i-1], bounds[i]]; bucket 0
        # is the underflow (<= lo), the last is the overflow (> hi).
        self.counts = [0] * (len(self.bounds) + 1)

    # ------------------------------------------------------------ hot

    def add(self, value: float) -> None:
        """One observation.  Two ops, no lock — the hot path."""
        self.counts[bisect_right(self.bounds, value)] += 1

    # ----------------------------------------------------------- cold

    @property
    def n(self) -> int:
        return sum(self.counts)

    def _bucket_value(self, idx: int) -> float:
        """Representative value of bucket ``idx``: geometric midpoint
        of its bounds (bounded relative error), clamped at the ladder
        edges."""
        if idx <= 0:
            return self.lo
        if idx >= len(self.bounds):
            return self.hi
        lo_b, hi_b = self.bounds[idx - 1], self.bounds[idx]
        return (lo_b * hi_b) ** 0.5

    def quantile(self, q: float) -> Optional[float]:
        """Value at rank ``q`` in [0, 1], or None while empty."""
        counts = list(self.counts)  # one racy snapshot, then stable
        total = sum(counts)
        if total <= 0:
            return None
        q = min(1.0, max(0.0, q))
        target = q * (total - 1)
        seen = 0
        for idx, c in enumerate(counts):
            if c <= 0:
                continue
            seen += c
            if seen - 1 >= target:
                return self._bucket_value(idx)
        return self._bucket_value(len(counts) - 1)

    def quantiles(self, qs) -> List[Optional[float]]:
        return [self.quantile(q) for q in qs]

    # ---------------------------------------------------------- merge

    def compatible(self, other: "RankSketch") -> bool:
        return (self.lo == other.lo and self.hi == other.hi
                and self.buckets_per_decade == other.buckets_per_decade)

    def merge(self, other: "RankSketch") -> "RankSketch":
        """Element-wise count addition into ``self`` (associative and
        commutative — the fleet-merge contract).  Raises on a ladder
        mismatch: merging incomparable ladders would silently produce
        garbage quantiles."""
        if not self.compatible(other):
            raise ValueError("sketch ladder mismatch")
        counts = self.counts
        for i, c in enumerate(other.counts):
            if c:
                counts[i] += c
        return self

    def copy(self) -> "RankSketch":
        dup = RankSketch(self.lo, self.hi, self.buckets_per_decade)
        dup.counts = list(self.counts)
        return dup

    def reset(self) -> None:
        self.counts = [0] * (len(self.bounds) + 1)

    # ----------------------------------------------------------- wire

    def to_doc(self) -> dict:
        """Sparse wire/persist form — gossip payloads and warm-state
        manifests carry only the occupied buckets."""
        return {
            "v": 1, "lo": self.lo, "hi": self.hi,
            "b": self.buckets_per_decade,
            "counts": {str(i): c for i, c in enumerate(self.counts)
                       if c},
        }

    @classmethod
    def from_doc(cls, doc) -> Optional["RankSketch"]:
        """Parse-or-None: a truncated or foreign doc merges as
        nothing, never as an exception (gossip payloads cross
        versions)."""
        if not isinstance(doc, dict) or doc.get("v") != 1:
            return None
        try:
            sk = cls(float(doc["lo"]), float(doc["hi"]),
                     int(doc["b"]))
            for key, c in dict(doc.get("counts") or {}).items():
                idx = int(key)
                if 0 <= idx < len(sk.counts):
                    sk.counts[idx] += int(c)
        except (KeyError, TypeError, ValueError):
            return None
        return sk
