"""Request tracing, bucketed histograms and health state.

The reference treats its perf4j stopwatch spans and Graphite metric
beans as first-class plumbing (``beanRefContext.xml:36-46``); this
module is the grown-up form of that layer for the TPU service:

* **Traces** — every HTTP request gets a trace id; spans recorded
  anywhere in the pipeline (frontend handler, sidecar dispatch, batcher
  group, device render, wire fetch) attach to the requesting trace(s)
  through a ``contextvars`` context, so one request yields a
  parent/child span waterfall even when its render rode a coalesced
  group with seven other requests.  The sidecar wire carries the trace
  id, so device-process spans join the frontend's trace.
* **Histograms** — fixed log-scale bucket latency distributions
  (Prometheus ``_bucket``/``_sum``/``_count`` semantics), replacing the
  p50-only ring that could not distinguish a tail regression from link
  weather.
* **Gauges** — link-health EWMA from the wire fetch observations
  (settles the weather-vs-structure question when a bench headline
  moves), XLA compile events (count + cumulative ms — a lazily compiled
  batch shape shows up here mechanically), queue depth and pipeline
  occupancy are read live from the batcher at scrape time.
* **Slow-request dumps** — requests over a configured threshold write
  their full waterfall JSON to a spool directory
  (``scripts/trace_report.py`` renders them).
* **Readiness** — process-wide degradation state behind ``/readyz``.

Device-free on import: nothing here pulls in JAX (frontends import this
module), and the compile listener only touches ``jax.monitoring`` when
a device-owning process installs it.
"""

from __future__ import annotations

import contextvars
import json
import logging
import os
import threading
import time
import uuid
from bisect import bisect_left
from contextlib import contextmanager
from typing import Dict, List, Mapping, Optional, Tuple

log = logging.getLogger("omero_ms_image_region_tpu.telemetry")

# --------------------------------------------------------------- histograms

# Fixed log-scale bucket bounds (ms): 0.25 ms .. ~32.8 s, ratio 2.
# Fixed — not adaptive — so series from different processes, restarts
# and dashboards always align bucket-for-bucket.
BUCKET_BOUNDS_MS: Tuple[float, ...] = tuple(0.25 * 2 ** i
                                            for i in range(18))


def _fmt(v: float) -> str:
    """Prometheus-friendly number formatting (no trailing zeros)."""
    return ("%g" % v)


class Histogram:
    """Cumulative log-bucket histogram (not thread-safe on its own;
    callers hold their registry lock around ``add``).

    With ``exemplars=True`` each bucket also keeps its most recent
    observation's exemplar — ``(trace_id, tier)`` from the caller —
    written as ONE list-slot assignment (GIL-atomic, lock-light: the
    hot path pays a tuple build and an index store), exposed in
    OpenMetrics exemplar syntax by :meth:`series`.  The p99 bucket
    then NAMES a trace id an operator can pull a waterfall for."""

    __slots__ = ("bounds", "counts", "sum", "count", "exemplars")

    def __init__(self, bounds: Tuple[float, ...] = BUCKET_BOUNDS_MS,
                 exemplars: bool = False):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)     # last = +Inf overflow
        self.sum = 0.0
        self.count = 0
        # bucket index -> (trace_id, tier, value, wall_ts) or None.
        self.exemplars = ([None] * (len(bounds) + 1) if exemplars
                          else None)

    def add(self, value: float,
            exemplar: Optional[Tuple[str, str]] = None) -> None:
        self.sum += value
        self.count += 1
        # bisect, not a linear bucket scan: add() sits on the span hot
        # path (every stage of every request lands here), and the scan
        # walked up to 18 bounds per observation.
        idx = bisect_left(self.bounds, value)
        self.counts[idx] += 1
        if self.exemplars is not None and exemplar is not None:
            # Slot write is a single GIL-atomic list assignment:
            # last-writer-wins is exactly the "most recent trace in
            # this bucket" semantics, so no lock is needed.
            self.exemplars[idx] = (exemplar[0], exemplar[1], value,
                                   time.time())

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (upper bound of the
        bucket holding the q-th sample) — keeps the old ring-p50 API
        alive for profiling scripts."""
        if not self.count:
            return 0.0
        target = max(1, int(q * self.count + 0.5))
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return (self.bounds[i] if i < len(self.bounds)
                        else self.bounds[-1] * 2)
        return self.bounds[-1] * 2

    def _exemplar_suffix(self, idx: int, enabled: bool) -> str:
        """OpenMetrics exemplar tail for one bucket line (empty when
        the bucket has none or the caller did not negotiate the
        OpenMetrics exposition): ``# {trace_id=..,tier=..} v ts``."""
        if not enabled or self.exemplars is None:
            return ""
        ex = self.exemplars[idx]
        if ex is None:
            return ""
        trace_id, tier, value, ts = ex
        return (f' # {{trace_id="{trace_id}",tier="{tier}"}} '
                f"{round(value, 3)} {round(ts, 3)}")

    def series(self, name: str, labels: str = "",
               exemplars: bool = False) -> List[str]:
        """Exposition lines.  ``labels`` is the inner label body without
        braces (e.g. ``route="x"``); ``le`` composes after it.
        ``exemplars`` opts the bucket lines into OpenMetrics exemplar
        tails — callers must pass True ONLY on a scrape that
        negotiated ``application/openmetrics-text`` (the classic
        text/plain parser rejects exemplar syntax, and one tail would
        fail the whole scrape)."""
        sep = "," if labels else ""
        lines = []
        cum = self.cumulative()
        for i, (b, c) in enumerate(zip(self.bounds, cum)):
            lines.append(f'{name}_bucket{{{labels}{sep}le="{_fmt(b)}"}}'
                         f" {c}{self._exemplar_suffix(i, exemplars)}")
        lines.append(f'{name}_bucket{{{labels}{sep}le="+Inf"}} '
                     f"{cum[-1]}"
                     f"{self._exemplar_suffix(len(self.bounds), exemplars)}")
        suffix = f"{{{labels}}}" if labels else ""
        lines.append(f"{name}_sum{suffix} {round(self.sum, 3)}")
        lines.append(f"{name}_count{suffix} {self.count}")
        return lines

    def exemplar_docs(self) -> List[dict]:
        """The live exemplars as JSON-able docs (the /debug/exemplars
        view: bucket upper bound -> most recent trace + tier)."""
        if self.exemplars is None:
            return []
        docs = []
        for i, ex in enumerate(list(self.exemplars)):
            if ex is None:
                continue
            le = (_fmt(self.bounds[i]) if i < len(self.bounds)
                  else "+Inf")
            docs.append({"le": le, "trace": ex[0], "tier": ex[1],
                         "value_ms": round(ex[2], 3),
                         "ts": round(ex[3], 3)})
        return docs


class HistogramVec:
    """Thread-safe histogram family keyed by one label value."""

    def __init__(self, label: str, exemplars: bool = False):
        self.label = label
        self.exemplars = exemplars
        self._lock = threading.Lock()
        self._hists: Dict[str, Histogram] = {}

    def observe(self, label_value: str, value: float,
                exemplar: Optional[Tuple[str, str]] = None) -> None:
        with self._lock:
            h = self._hists.get(label_value)
            if h is None:
                h = self._hists[label_value] = Histogram(
                    exemplars=self.exemplars)
            h.add(value, exemplar=exemplar)

    def series(self, name: str,
               exemplars: bool = False) -> List[str]:
        with self._lock:
            items = sorted(self._hists.items())
            lines = []
            for lv, h in items:
                lines += h.series(name, f'{self.label}="{lv}"',
                                  exemplars=exemplars)
            return lines

    def exemplar_docs(self) -> Dict[str, List[dict]]:
        """{label_value: [bucket exemplar docs]} — /debug/exemplars."""
        with self._lock:
            items = sorted(self._hists.items())
        return {lv: docs for lv, h in items
                if (docs := h.exemplar_docs())}

    def reset(self) -> None:
        with self._lock:
            self._hists.clear()


# End-to-end request latency by route — the acceptance-criteria series.
# Exemplared: each bucket names the most recent trace id + provenance
# tier that landed in it, so the p99 bucket points at a pullable
# waterfall (the metrics -> trace loop).
REQUEST_HIST = HistogramVec("route", exemplars=True)
_REQ_LOCK = threading.Lock()
_REQ_TOTALS: Dict[tuple, int] = {}


def count_request(route: str, status: int) -> None:
    with _REQ_LOCK:
        key = (route, int(status))
        _REQ_TOTALS[key] = _REQ_TOTALS.get(key, 0) + 1


# ------------------------------------------------------------------- traces

def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


class Trace:
    __slots__ = ("trace_id", "route", "t0", "wall_ts", "spans", "lock",
                 "costs")

    def __init__(self, trace_id: str, route: str = ""):
        self.trace_id = trace_id
        self.route = route
        self.t0 = time.perf_counter()
        self.wall_ts = time.time()
        self.spans: List[dict] = []
        # Per-request cost ledger: numeric accumulators attributed to
        # this request (device-execute ms pro-rata from its batch
        # group, staged vs dedup-skipped HBM bytes, ...).  Written by
        # whatever layer did the work — batcher worker threads, the
        # device cache, the sidecar wire graft — under ``lock``.
        self.costs: Dict[str, float] = {}
        self.lock = threading.Lock()

    def add_cost(self, key: str, value: float) -> None:
        with self.lock:
            self.costs[key] = self.costs.get(key, 0.0) + float(value)

    def add_costs(self, items: Mapping[str, float]) -> None:
        """Batched ledger update: one lock acquisition for the whole
        mapping (the batcher flushes several fields per group; a lock
        round-trip per field was pure hot-path tax)."""
        with self.lock:
            costs = self.costs
            for key, value in items.items():
                costs[key] = costs.get(key, 0.0) + float(value)

    def export_costs(self) -> Dict[str, float]:
        """Wire-safe copy of the ledger (the sidecar response carries
        it so device-side costs land on the frontend's ledger)."""
        with self.lock:
            return dict(self.costs)

    def add_span(self, name: str, t_start: float, dur_ms: float,
                 **meta) -> None:
        span = {"name": name,
                "start_ms": round((t_start - self.t0) * 1000.0, 3),
                "dur_ms": round(dur_ms, 3)}
        if meta:
            span.update(meta)
        # Lock-free: list.append is atomic under the GIL, and every
        # reader below snapshots via list(self.spans) (also atomic)
        # before iterating — spans are recorded on the request path,
        # so the per-span lock round-trip was the single hottest
        # telemetry cost in the PR 4/5 profile.
        self.spans.append(span)

    def export_spans(self) -> List[dict]:
        """Copied span list (wire-safe: plain JSON dicts whose
        ``start_ms`` offsets are relative to this trace's t0)."""
        return [dict(s) for s in list(self.spans)]

    def span_ms(self, *names: str) -> Optional[float]:
        """Total duration of spans with one of the EXACT ``names``
        (None when the request never touched those stages).  Exact, not
        prefix: "Renderer.renderAsPackedInt" must not also sum its
        nested ".batch" child or totals exceed the request wall time."""
        total, seen = 0.0, False
        for s in list(self.spans):
            if s["name"] in names:
                total += s["dur_ms"]
                seen = True
        return total if seen else None

    def to_json(self, total_ms: Optional[float] = None,
                status: Optional[int] = None) -> dict:
        spans = sorted(list(self.spans), key=lambda s: s["start_ms"])
        with self.lock:
            costs = dict(self.costs)
        doc = {"trace_id": self.trace_id, "route": self.route,
               "ts": self.wall_ts, "spans": spans}
        if costs:
            doc["cost"] = {k: round(v, 3) for k, v in costs.items()}
        if total_ms is not None:
            doc["total_ms"] = round(total_ms, 3)
        if status is not None:
            doc["status"] = status
        return doc


class TraceRegistry:
    """Active traces by id, bounded; finished traces keep a short ring
    for tests and ad-hoc inspection.

    A sidecar process records spans for trace ids it never started (the
    frontend owns the request); those auto-created entries are evicted
    oldest-first once ``max_active`` is exceeded, so an orphaned trace
    can never leak memory."""

    def __init__(self, max_active: int = 4096, recent: int = 64):
        self._lock = threading.Lock()
        self._active: Dict[str, Trace] = {}
        self._max_active = max_active
        from collections import deque
        self.recent = deque(maxlen=recent)

    def start(self, trace_id: str, route: str = "") -> Trace:
        trace = Trace(trace_id, route)
        with self._lock:
            self._active[trace_id] = trace
            while len(self._active) > self._max_active:
                self._active.pop(next(iter(self._active)))
        return trace

    def get_or_create(self, trace_id: str) -> Trace:
        # Lock-free fast path: dict.get is GIL-atomic, and this lookup
        # runs once per span per trace (the hottest telemetry call in
        # the serving profile) — only the create takes the lock.  A
        # concurrent eviction racing the get just falls through to the
        # locked path.
        trace = self._active.get(trace_id)
        if trace is not None:
            return trace
        with self._lock:
            trace = self._active.get(trace_id)
            if trace is None:
                trace = self._active[trace_id] = Trace(trace_id)
                while len(self._active) > self._max_active:
                    self._active.pop(next(iter(self._active)))
            return trace

    def is_active(self, trace_id: str) -> bool:
        with self._lock:
            return trace_id in self._active

    def finish(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            trace = self._active.pop(trace_id, None)
        if trace is not None:
            self.recent.append(trace)
        return trace

    def reset(self) -> None:
        with self._lock:
            self._active.clear()
        self.recent.clear()


TRACES = TraceRegistry()

# The trace ids the CURRENT execution context is working for.  A plain
# request context carries one id; a batcher worker thread rendering a
# coalesced group carries every member's id, so the one group-render
# span lands on all of their waterfalls.
_TRACE_IDS: contextvars.ContextVar[Tuple[str, ...]] = \
    contextvars.ContextVar("imageregion_trace_ids", default=())

# Registry values recorded through the stopwatch registry that are NOT
# durations (counts etc.) — excluded from trace waterfalls.
_NON_SPAN_NAMES = frozenset({"batcher.groupTiles"})


def current_trace_ids() -> Tuple[str, ...]:
    return _TRACE_IDS.get()


def current_trace_id() -> Optional[str]:
    ids = _TRACE_IDS.get()
    return ids[0] if ids else None


def clear_context() -> None:
    """Detach the current execution context from any trace.  Long-lived
    tasks spawned from inside a request (the batcher's per-key
    dispatcher loops) MUST call this: contextvars copy at task creation,
    and without it every span the task ever records would attach to the
    spawning request's waterfall."""
    _TRACE_IDS.set(())


@contextmanager
def trace_scope(trace_id: str, route: str = ""):
    """Root scope for one request: registers the trace, makes it the
    context's recording target, yields the Trace (the caller finishes
    it — the finish policy lives with the HTTP layer)."""
    trace = TRACES.start(trace_id, route)
    token = _TRACE_IDS.set((trace_id,))
    try:
        yield trace
    finally:
        _TRACE_IDS.reset(token)


@contextmanager
def adopt_trace(trace_id: Optional[str]):
    """Join an existing trace (sidecar side of the wire): spans recorded
    inside attach to ``trace_id``'s waterfall.  No-op for None."""
    if not trace_id:
        yield None
        return
    trace = TRACES.get_or_create(trace_id)
    token = _TRACE_IDS.set((trace_id,))
    try:
        yield trace
    finally:
        _TRACE_IDS.reset(token)


@contextmanager
def group_trace(trace_ids: Tuple[str, ...]):
    """Recording target for a batcher worker thread rendering a
    coalesced group: spans land on EVERY member's waterfall."""
    token = _TRACE_IDS.set(tuple(trace_ids))
    try:
        yield
    finally:
        _TRACE_IDS.reset(token)


def record_span(name: str, t_start: float, dur_ms: float,
                trace_ids: Optional[Tuple[str, ...]] = None,
                **meta) -> None:
    """Attach a span to the given traces (default: the context's)."""
    ids = trace_ids if trace_ids is not None else _TRACE_IDS.get()
    for tid in ids:
        trace = TRACES.get_or_create(tid)
        trace.add_span(name, t_start, dur_ms, **meta)


def observe_span(name: str, dur_ms: float) -> None:
    """Hook for the stopwatch registry: every recorded stage duration
    becomes a child span on whatever traces the context carries."""
    if name in _NON_SPAN_NAMES:
        return
    ids = _TRACE_IDS.get()
    if not ids:
        return
    record_span(name, time.perf_counter() - dur_ms / 1000.0, dur_ms,
                trace_ids=ids)


def add_cost(key: str, value: float,
             trace_ids: Optional[Tuple[str, ...]] = None) -> None:
    """Accumulate a cost onto the context's trace ledger(s).

    Pro-rata attribution is the CALLER's job: a batcher group render
    running under ``group_trace`` passes ``exec_ms / len(group)`` and
    every member's ledger receives its fair share of the one device
    dispatch.  No-op outside any trace context (prefetchers, prewarm)."""
    ids = trace_ids if trace_ids is not None else _TRACE_IDS.get()
    for tid in ids:
        TRACES.get_or_create(tid).add_cost(key, value)


def add_costs(items: Mapping[str, float],
              trace_ids: Optional[Tuple[str, ...]] = None) -> None:
    """Batched :func:`add_cost`: the whole mapping lands under ONE lock
    per trace (pay-for-what-you-use: a group render flushes its ledger
    fields in one shot instead of a lock round-trip per field)."""
    ids = trace_ids if trace_ids is not None else _TRACE_IDS.get()
    if not ids or not items:
        return
    for tid in ids:
        TRACES.get_or_create(tid).add_costs(items)


def merge_costs(trace_id: str, costs: Dict[str, float]) -> None:
    """Graft a wire-exported ledger (sidecar response) onto a trace."""
    trace = TRACES.get_or_create(trace_id)
    for key, value in costs.items():
        try:
            trace.add_cost(str(key), float(value))
        except (TypeError, ValueError):
            pass    # malformed wire field: drop it, keep serving


# ------------------------------------------------------------- link health

class LinkHealth:
    """EWMAs of the device->host link rate, fed by the wire fetchers
    (``ops.jpegenc._observe_fetch``).

    Two gauges, because almost every PRIMARY prefetch is ``conflated``
    (its timed window covers device execution as well as the transfer):

    * ``effective_mb_s`` — EWMA over ALL bandwidth-class fetches, both
      directions.  This is the rate requests actually experience, and
      the one that TRACKS a link slowdown (a conflated-only stream
      would otherwise never move a lower bound downward).
    * ``ewma_mb_s`` — floor estimate of the RAW link: conflated
      observations update it only upward (a conflated 40 MB/s proves
      the link is at least that fast; a conflated 2 MB/s proves
      nothing — it may be compile or execution stall, not wire).

    Effective falling while the floor holds reads as device-side
    weather; both falling together is the link itself.
    """

    MIN_BYTES = 256 * 1024      # below this, latency dominates

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self._lock = threading.Lock()
        self.ewma_mb_s: Optional[float] = None
        self.effective_mb_s: Optional[float] = None
        self.fetches = 0
        self.bytes_total = 0
        self.last_ts = 0.0

    def _blend(self, prev: Optional[float], rate: float) -> float:
        return rate if prev is None else prev + self.alpha * (rate
                                                              - prev)

    def observe(self, nbytes: int, seconds: float,
                conflated: bool = False) -> None:
        with self._lock:
            self.fetches += 1
            self.bytes_total += int(nbytes)
            self.last_ts = time.time()
            if seconds <= 0 or nbytes < self.MIN_BYTES:
                return
            rate = nbytes / seconds / 1e6
            self.effective_mb_s = self._blend(self.effective_mb_s,
                                              rate)
            if conflated and (self.ewma_mb_s is not None
                              and rate <= self.ewma_mb_s):
                return
            self.ewma_mb_s = self._blend(self.ewma_mb_s, rate)

    def reset(self) -> None:
        with self._lock:
            self.ewma_mb_s = None
            self.effective_mb_s = None
            self.fetches = 0
            self.bytes_total = 0
            self.last_ts = 0.0


LINK = LinkHealth()


# ---------------------------------------------------------- compile events

class CompileStats:
    """XLA compile activity: count + cumulative ms of backend compiles.

    A serving-path program shape that was missed by prewarm shows up
    here as a count increment with a seconds-scale duration — the
    mechanical detector for first-touch compile stalls."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events = 0
        self.total_ms = 0.0

    def observe(self, duration_s: float) -> None:
        with self._lock:
            self.events += 1
            self.total_ms += duration_s * 1000.0
        # Compile stalls are exactly the "what was it doing before it
        # fell over" class the black box exists for.
        FLIGHT.record("xla.compile", ms=round(duration_s * 1000.0, 1))

    def reset(self) -> None:
        with self._lock:
            self.events = 0
            self.total_ms = 0.0


COMPILE = CompileStats()
_COMPILE_LISTENER = threading.Lock()
_compile_listener_installed = False


# --------------------------------------------------------- cost ledger

# Per-route histograms over the request cost ledger — which requests
# are expensive, and WHERE the expense sits (device, queue, staging,
# encode, wire).  Keys are the ledger fields; byte fields convert to
# KB so the fixed ms-scale log buckets still resolve them.
_COST_HIST_FIELDS = {
    "device_ms": "imageregion_request_cost_device_ms",
    "read_ms": "imageregion_request_cost_read_ms",
    "stage_ms": "imageregion_request_cost_stage_ms",
    "queue_ms": "imageregion_request_cost_queue_ms",
    "encode_ms": "imageregion_request_cost_encode_ms",
    "staged_kb": "imageregion_request_cost_staged_kb",
    "wire_kb": "imageregion_request_cost_wire_kb",
}

COST_HISTS: Dict[str, HistogramVec] = {
    field: HistogramVec("route") for field in _COST_HIST_FIELDS
}


class CostTopK:
    """Bounded ledger of the most expensive recent requests (by wall
    total_ms) — the ``/debug/costs`` answer to "which requests are
    expensive".  Thread-safe; eviction is cheapest-first."""

    def __init__(self, k: int = 16):
        self.k = k
        self._lock = threading.Lock()
        self._entries: List[dict] = []   # sorted descending by score
        self.observed = 0

    def offer(self, doc: dict) -> None:
        score = float(doc.get("total_ms") or 0.0)
        with self._lock:
            self.observed += 1
            if (len(self._entries) >= self.k
                    and score <= float(
                        self._entries[-1].get("total_ms") or 0.0)):
                return
            self._entries.append(doc)
            self._entries.sort(key=lambda d: -(d.get("total_ms") or 0.0))
            del self._entries[self.k:]

    def snapshot(self) -> List[dict]:
        with self._lock:
            return [dict(d) for d in self._entries]

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self.observed = 0


COST_TOPK = CostTopK()


def assemble_ledger(trace: Trace, total_ms: float,
                    nbytes: int) -> Tuple[Dict[str, float], str]:
    """(ledger, cache_class) for a finished request.

    Accumulated costs (device/stage ms, staged bytes — written by the
    layers that did the work) merge with span-derived fields (queue
    wait, encode) and the response size.  ``cache_class`` is where the
    bytes came from: ``byte-cache`` (no pipeline ran), ``coalesced``
    (single-flight follower), else ``render``."""
    ledger = trace.export_costs()
    queue_ms = trace.span_ms("batcher.queueWait")
    if queue_ms is not None:
        ledger["queue_ms"] = round(queue_ms, 3)
    read_ms = trace.span_ms("PixelsService.readRegion")
    if read_ms is not None:
        ledger["read_ms"] = round(read_ms, 3)
    encode_ms = trace.span_ms("encodeImage", "jfif.encodeBatch")
    if encode_ms is not None:
        ledger["encode_ms"] = round(encode_ms, 3)
    ledger["wire_bytes"] = int(nbytes)
    ledger["total_ms"] = round(total_ms, 3)
    if trace.span_ms("cache.hit") is not None:
        cache_class = "byte-cache"
    elif trace.span_ms("dedup.coalesced") is not None:
        cache_class = "coalesced"
    else:
        cache_class = "render"
    return ledger, cache_class


def observe_request_cost(route: str, ledger: Dict[str, float]) -> None:
    """Feed the per-route cost histograms from a finished ledger."""
    for field, hist in COST_HISTS.items():
        if field == "staged_kb":
            value = ledger.get("staged_bytes")
        elif field == "wire_kb":
            value = ledger.get("wire_bytes")
        else:
            value = ledger.get(field)
        if value is None:
            continue
        if field.endswith("_kb"):
            value = float(value) / 1024.0
        hist.observe(route, float(value))


def cost_metric_lines() -> List[str]:
    lines: List[str] = []
    for field, hist in COST_HISTS.items():
        lines += hist.series(_COST_HIST_FIELDS[field])
    return lines


# ------------------------------------------------------ flight recorder

# Monotone artifact sequence shared by flight dumps and profile
# captures: two artifacts in the same wall-clock second must get two
# names, never silently overwrite one (next() is atomic on CPython).
import itertools as _itertools          # noqa: E402

_ARTIFACT_SEQ = _itertools.count(1)


class FlightRecorder:
    """Black-box ring of structured events: what the system was doing
    in the seconds before it fell over.

    Lock-free on the hot path — ``deque.append`` with a ``maxlen`` is
    atomic under the GIL, so recording from batcher worker threads,
    the admission path and (best-effort) signal handlers never blocks
    and never deadlocks.  ``dump`` snapshots via ``list(ring)`` (also
    atomic) and NEVER raises: a full disk must not turn a crash dump
    into a second crash."""

    def __init__(self, maxlen: int = 512):
        from collections import deque
        self._ring = deque(maxlen=maxlen)
        self.events_total = 0
        self.dumps_written = 0
        # Fleet identity stamp: when set (a process that knows which
        # member it is), every recorded event carries it, so merged
        # fleet rings stay attributable (events that already name a
        # member — drain phases, steals — keep their own).
        self.member: Optional[str] = None

    def configure(self, maxlen: int,
                  member: Optional[str] = None) -> None:
        from collections import deque
        if maxlen != self._ring.maxlen:
            self._ring = deque(self._ring, maxlen=max(16, maxlen))
        if member is not None:
            self.member = member

    def set_member(self, member: Optional[str]) -> None:
        self.member = member

    def record(self, kind: str, **fields) -> None:
        event = {"ts": round(time.time(), 3), "kind": kind}
        if self.member is not None and "member" not in fields:
            event["member"] = self.member
        if fields:
            event.update(fields)
        self._ring.append(event)
        self.events_total += 1    # benign race: a count, not a key

    def snapshot(self) -> List[dict]:
        return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)

    # Spool retention: dumps past this many are pruned oldest-first on
    # each write, so a breach-flapping (or curl-looping) deployment
    # cannot fill the disk with black-box snapshots.
    MAX_DUMPS = 64

    def dump(self, directory: str, reason: str) -> Optional[str]:
        """Write the ring as one JSON document; returns the path or
        None (never raises — see class docstring).  Names carry a
        monotone sequence so same-second dumps never collide."""
        try:
            events = self.snapshot()
            os.makedirs(directory, exist_ok=True)
            seq = next(_ARTIFACT_SEQ)
            path = os.path.join(
                directory,
                time.strftime(f"flight-%Y%m%d-%H%M%S-{os.getpid()}"
                              f"-{seq:04d}-{reason}.json"))
            doc = {"flight_recorder": True, "reason": reason,
                   "ts": round(time.time(), 3), "pid": os.getpid(),
                   "events": events}
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
            self.dumps_written += 1
            self._prune(directory)
            return path
        except Exception:
            try:
                log.warning("flight-recorder dump to %s failed",
                            directory, exc_info=True)
            except Exception:
                pass
            return None

    def _prune(self, directory: str) -> None:
        dumps = sorted(
            os.path.join(directory, name)
            for name in os.listdir(directory)
            if name.startswith("flight-") and name.endswith(".json"))
        for stale in dumps[:-self.MAX_DUMPS]:
            try:
                os.unlink(stale)
            except OSError:
                pass

    def reset(self) -> None:
        self._ring.clear()
        self.events_total = 0
        self.dumps_written = 0
        self.member = None


FLIGHT = FlightRecorder()


# ----------------------------------------------------------- SLO engine

class SloEngine:
    """Config-declared service objectives evaluated as multi-window
    burn rates (the Google SRE alerting form: error_rate /
    error_budget over a fast AND a slow window — both over threshold
    means the budget is burning fast enough, for long enough, to
    matter).

    Objectives:

    * ``availability`` — fraction of requests answering below 500
      (deliberate sheds and deadline 504s spend the budget: the user
      still did not get a tile);
    * ``latency`` — fraction of SUCCESSFUL requests under
      ``latency_ms`` (the p-target latency objective; errors are the
      availability objective's problem, not this one's).

    Time is bucketed (``BUCKET_S``) so the windows are O(window /
    bucket) memory and record() is a dict increment.  Disabled (the
    default — no targets configured) it costs one boolean check.
    A breach TRANSITION fires ``on_breach`` once per episode — the
    flight-recorder dump hook."""

    BUCKET_S = 5.0

    def __init__(self):
        self._lock = threading.Lock()
        self._clock = time.monotonic
        self.enabled = False
        self.availability_target = 0.0
        self.latency_ms = 0.0
        self.latency_target = 0.99
        self.fast_window_s = 60.0
        self.slow_window_s = 600.0
        self.breach_burn_rate = 14.4
        self.on_breach = None
        self.breached: Dict[str, bool] = {}
        self.breaches_total = 0
        # bucket index -> {"good": n, "bad": n, "fast": n, "slow": n}
        self._buckets: Dict[int, Dict[str, int]] = {}

    def configure(self, availability_target: float = 0.0,
                  latency_ms: float = 0.0,
                  latency_target: float = 0.99,
                  fast_window_s: float = 60.0,
                  slow_window_s: float = 600.0,
                  breach_burn_rate: float = 14.4,
                  on_breach=None, clock=time.monotonic) -> None:
        with self._lock:
            self.availability_target = availability_target
            self.latency_ms = latency_ms
            self.latency_target = latency_target
            self.fast_window_s = fast_window_s
            self.slow_window_s = max(slow_window_s, fast_window_s)
            self.breach_burn_rate = breach_burn_rate
            self.on_breach = on_breach
            self._clock = clock
            self.enabled = bool(availability_target or latency_ms)
            self._buckets.clear()
            self.breached = {}

    def _bucket(self, now: float) -> Dict[str, int]:
        idx = int(now // self.BUCKET_S)
        b = self._buckets.get(idx)
        if b is None:
            b = self._buckets[idx] = {"ok": 0, "err": 0,
                                      "fast": 0, "slow": 0}
            # Prune everything older than the slow window.
            floor = idx - int(self.slow_window_s // self.BUCKET_S) - 1
            for old in [i for i in self._buckets if i < floor]:
                del self._buckets[old]
        return b

    def record(self, status: int, dur_ms: float) -> None:
        if not self.enabled:
            return
        breach_cbs = []
        with self._lock:
            b = self._bucket(self._clock())
            if status >= 500:
                b["err"] += 1
            else:
                b["ok"] += 1
                if self.latency_ms:
                    if dur_ms <= self.latency_ms:
                        b["fast"] += 1
                    else:
                        b["slow"] += 1
            rates = self._burn_rates_locked()
            for objective, (fast, slow) in rates.items():
                now_breached = (fast >= self.breach_burn_rate
                                and slow >= self.breach_burn_rate)
                was = self.breached.get(objective, False)
                self.breached[objective] = now_breached
                if now_breached and not was:
                    self.breaches_total += 1
                    # Appended, not assigned: both objectives may
                    # transition on ONE record, and each breach owns
                    # its dump.
                    breach_cbs.append((objective, fast, slow))
        if self.on_breach is not None:
            for cb in breach_cbs:
                try:
                    self.on_breach(*cb)
                except Exception:  # forensics must never fail requests
                    log.warning("SLO on_breach hook failed",
                                exc_info=True)

    def _window_counts(self, window_s: float) -> Dict[str, int]:
        floor = int((self._clock() - window_s) // self.BUCKET_S)
        out = {"ok": 0, "err": 0, "fast": 0, "slow": 0}
        for idx, b in self._buckets.items():
            if idx >= floor:
                for k in out:
                    out[k] += b[k]
        return out

    def _burn_rates_locked(self) -> Dict[str, Tuple[float, float]]:
        rates: Dict[str, Tuple[float, float]] = {}

        def burn(bad: int, total: int, target: float) -> float:
            if total == 0:
                return 0.0
            budget = max(1e-9, 1.0 - target)
            return (bad / total) / budget

        pair = []
        for window_s in (self.fast_window_s, self.slow_window_s):
            pair.append(self._window_counts(window_s))
        if self.availability_target:
            rates["availability"] = tuple(
                burn(c["err"], c["ok"] + c["err"],
                     self.availability_target) for c in pair)
        if self.latency_ms:
            rates["latency"] = tuple(
                burn(c["slow"], c["fast"] + c["slow"],
                     self.latency_target) for c in pair)
        return rates

    def burn_rates(self) -> Dict[str, Tuple[float, float]]:
        """{objective: (fast_burn, slow_burn)} over the two windows."""
        with self._lock:
            return self._burn_rates_locked()

    def any_breached(self) -> bool:
        with self._lock:
            return any(self.breached.values())

    def summary(self) -> str:
        """One-line state for the /readyz annotation."""
        with self._lock:
            rates = self._burn_rates_locked()
            breached = [o for o, v in self.breached.items() if v]
        if not rates:
            return "disabled"
        parts = [f"{o} burn {fast:.1f}/{slow:.1f}"
                 for o, (fast, slow) in sorted(rates.items())]
        state = "BREACH " if breached else "ok "
        return state + ", ".join(parts)

    def metric_lines(self) -> List[str]:
        if not self.enabled:
            return []
        lines = []
        with self._lock:
            rates = self._burn_rates_locked()
            breached = dict(self.breached)
            breaches = self.breaches_total
        for objective, (fast, slow) in sorted(rates.items()):
            for window, rate in (("fast", fast), ("slow", slow)):
                lines.append(
                    f'imageregion_slo_burn_rate{{slo="{objective}",'
                    f'window="{window}"}} {round(rate, 4)}')
            lines.append(
                f'imageregion_slo_breach{{slo="{objective}"}} '
                f'{1 if breached.get(objective) else 0}')
        lines.append(f"imageregion_slo_breaches_total {breaches}")
        return lines

    def export_buckets(self) -> dict:
        """Wire-portable window state for fleet-level aggregation
        (``FleetSloStats``).  Bucket indices key off this process's
        monotonic clock, which means nothing on another host — so
        buckets cross the wire as AGES (seconds before this export),
        and the ingesting side re-anchors them against its own clock
        at ingest time.  Disabled engines export ``{}`` (the
        emit-when-live posture: a host with no objectives contributes
        nothing to the fleet burn)."""
        with self._lock:
            if not self.enabled:
                return {}
            now = self._clock()
            buckets = [
                [round(now - idx * self.BUCKET_S, 3),
                 b["ok"], b["err"], b["fast"], b["slow"]]
                for idx, b in sorted(self._buckets.items())
            ]
            return {
                "bucket_s": self.BUCKET_S,
                "availability_target": self.availability_target,
                "latency_ms": self.latency_ms,
                "latency_target": self.latency_target,
                "fast_window_s": self.fast_window_s,
                "slow_window_s": self.slow_window_s,
                "buckets": buckets,
            }

    def reset(self) -> None:
        with self._lock:
            self.enabled = False
            self.availability_target = 0.0
            self.latency_ms = 0.0
            self.on_breach = None
            self._clock = time.monotonic
            self._buckets.clear()
            self.breached = {}
            self.breaches_total = 0


SLO = SloEngine()


# ------------------------------------------------------ shape cost model

class ShapeCostModel:
    """Estimated vs observed device cost per compiled render shape.

    The batcher records every group's device-execute wall ms under its
    ladder-shape label, and (once per shape, best-effort) the XLA
    ``cost_analysis()`` flops/bytes estimate of the compiled program —
    so /metrics answers "is this shape running at the speed its
    program says it should" without a profiler attached.  Label
    cardinality is bounded by the bucket/batch ladder."""

    def __init__(self):
        self._lock = threading.Lock()
        self._shapes: Dict[str, dict] = {}
        self._claimed: set = set()

    def observe(self, shape: str, ms: float) -> None:
        with self._lock:
            s = self._shapes.get(shape)
            if s is None:
                s = self._shapes[shape] = {
                    "dispatches": 0, "ms_total": 0.0,
                    "est_flops": None, "est_bytes": None}
            s["dispatches"] += 1
            s["ms_total"] += ms

    def claim_estimate(self, shape: str) -> bool:
        """One-shot claim of the estimate capture for ``shape`` — True
        exactly once, so concurrent first groups of one shape spawn
        one capture, not one per lane."""
        with self._lock:
            if shape in self._claimed:
                return False
            self._claimed.add(shape)
            return True

    def set_estimate(self, shape: str, flops: Optional[float],
                     nbytes: Optional[float]) -> None:
        with self._lock:
            s = self._shapes.setdefault(shape, {
                "dispatches": 0, "ms_total": 0.0,
                "est_flops": None, "est_bytes": None})
            # 0.0 marks "capture attempted, nothing learned" so the
            # one-time hook never re-fires for the shape.
            s["est_flops"] = float(flops or 0.0)
            s["est_bytes"] = float(nbytes or 0.0)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._shapes.items()}

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        lines = []
        extra = extra_labels.lstrip(",")
        with self._lock:
            items = sorted(self._shapes.items())
        for shape, s in items:
            lb = f'{{shape="{shape}"' + (f",{extra}" if extra
                                         else "") + "}"
            lines += [
                f"imageregion_shape_dispatches_total{lb} "
                f"{s['dispatches']}",
                f"imageregion_shape_device_ms_total{lb} "
                f"{round(s['ms_total'], 3)}",
            ]
            if s["dispatches"]:
                lines.append(
                    f"imageregion_shape_device_ms_mean{lb} "
                    f"{round(s['ms_total'] / s['dispatches'], 3)}")
            if s["est_flops"] is not None:
                lines += [
                    f"imageregion_shape_estimated_flops{lb} "
                    f"{_fmt(s['est_flops'])}",
                    f"imageregion_shape_estimated_bytes{lb} "
                    f"{_fmt(s['est_bytes'])}",
                ]
        return lines

    def reset(self) -> None:
        with self._lock:
            self._shapes.clear()
            self._claimed.clear()


SHAPE_COSTS = ShapeCostModel()


# ------------------------------------------------------ device profiling

class ProfileInProgressError(Exception):
    """A capture is already running (the endpoint answers 409)."""


_PROFILE_LOCK = threading.Lock()


def capture_profile(directory: str, ms: float) -> dict:
    """Wrap ``jax.profiler`` around whatever the device is doing for
    ``ms`` milliseconds; returns the artifact manifest.

    Single-flight (`ProfileInProgressError` when one is live —
    concurrent captures would interleave one trace file), blocking
    (call via a worker thread), and the ONE telemetry function besides
    the compile listener that imports JAX — only device-owning
    processes serve it (frontends forward over the sidecar wire)."""
    if not _PROFILE_LOCK.acquire(blocking=False):
        raise ProfileInProgressError("a profile capture is already "
                                     "running")
    try:
        import jax
        seq = next(_ARTIFACT_SEQ)
        path = os.path.join(
            directory,
            time.strftime(f"profile-%Y%m%d-%H%M%S-{seq:04d}"))
        os.makedirs(path, exist_ok=True)
        t0 = time.perf_counter()
        jax.profiler.start_trace(path)
        try:
            time.sleep(max(0.0, ms) / 1000.0)
        finally:
            jax.profiler.stop_trace()
        files = []
        total = 0
        for root, _dirs, names in os.walk(path):
            for name in names:
                full = os.path.join(root, name)
                files.append(os.path.relpath(full, path))
                try:
                    total += os.path.getsize(full)
                except OSError:
                    pass
        FLIGHT.record("profile.captured", dir=path,
                      ms=round(ms, 1), files=len(files))
        return {"dir": path, "ms": round(
            (time.perf_counter() - t0) * 1000.0, 1),
            "requested_ms": ms, "files": sorted(files),
            "bytes": total}
    finally:
        _PROFILE_LOCK.release()


def install_compile_listener() -> bool:
    """Register the jax.monitoring listener (device processes only —
    this is the one function here that imports JAX).  Idempotent;
    returns whether the listener is active."""
    global _compile_listener_installed
    with _COMPILE_LISTENER:
        if _compile_listener_installed:
            return True
        try:
            from jax import monitoring
        except Exception:       # pragma: no cover - jax-free frontends
            return False

        def _on_event(event: str, duration: float, **kw) -> None:
            # backend_compile is the actual XLA compile; trace/lowering
            # events would double-count the same program.
            if "backend_compile" in event:
                COMPILE.observe(duration)

        try:
            monitoring.register_event_duration_secs_listener(_on_event)
        except Exception:       # pragma: no cover - API drift
            return False
        _compile_listener_installed = True
        return True


# --------------------------------------------------------------- resilience

class Resilience:
    """Fault-tolerance accounting behind /metrics: sheds, deadline
    cancellations, sidecar retries, degraded-mode renders, supervisor
    restarts.  Thread-safe — the batcher's worker threads and the
    supervisor's monitor thread both count here."""

    def __init__(self):
        self._lock = threading.Lock()
        self.shed: Dict[str, int] = {}            # reason -> count
        self.retries: Dict[str, int] = {}         # op -> retry count
        self.deadline_cancelled = 0
        self.degraded_renders = 0
        self.supervisor_restarts = 0
        # Attempts actually used per sidecar call, by op (a histogram,
        # not a mean: "most calls take 1, a few take 3" is the signal).
        self.attempts_hist = HistogramVec("op")

    def count_shed(self, reason: str = "queue-full") -> None:
        with self._lock:
            self.shed[reason] = self.shed.get(reason, 0) + 1

    def count_retry(self, op: str) -> None:
        with self._lock:
            self.retries[op] = self.retries.get(op, 0) + 1

    def count_deadline_cancelled(self, n: int = 1) -> None:
        with self._lock:
            self.deadline_cancelled += n

    def count_degraded_render(self) -> None:
        with self._lock:
            self.degraded_renders += 1

    def count_supervisor_restart(self) -> None:
        with self._lock:
            self.supervisor_restarts += 1

    def observe_attempts(self, op: str, attempts: int) -> None:
        self.attempts_hist.observe(op, float(attempts))

    def reset(self) -> None:
        with self._lock:
            self.shed.clear()
            self.retries.clear()
            self.deadline_cancelled = 0
            self.degraded_renders = 0
            self.supervisor_restarts = 0
        self.attempts_hist.reset()


RESILIENCE = Resilience()


# ------------------------------------------------------ warm persistence

class Persistence:
    """Warm-state persistence accounting (services.diskcache +
    services.warmstate + server.execcache): disk byte-cache write/
    corruption counters, snapshot age/duration, and live rehydrate
    progress.  Thread-safe — the disk tier's write-behind worker, the
    snapshot timer thread and the boot rehydrator all count here; the
    scrape path only reads."""

    def __init__(self):
        self._lock = threading.Lock()
        # Disk byte-cache tier (services.diskcache.DiskByteCache).
        self.diskcache_writes = 0
        self.diskcache_write_errors = 0
        self.diskcache_write_dropped = 0
        self.diskcache_corrupt = 0
        self.diskcache_bytes = 0          # gauge (set by the cache)
        self.diskcache_entries = 0        # gauge
        # Snapshot engine (services.warmstate).
        self.snapshots = 0
        self.snapshot_errors = 0
        self.snapshot_last_ts = 0.0       # wall clock of the last write
        self.snapshot_duration_ms = 0.0
        # Boot rehydrator progress (the /readyz annotation + gauges).
        self.rehydrate_running = False
        self.rehydrate_items_total = 0
        self.rehydrate_items_done = 0
        self.rehydrate_errors = 0
        self.rehydrate_aborted = False
        self.rehydrate_duration_ms = 0.0
        self.rehydrate_bytes_promoted = 0
        self.rehydrate_planes_restaged = 0
        self.rehydrate_executables_loaded = 0

    # ------------------------------------------------------- disk tier

    def count_disk_write(self, error: bool = False,
                         dropped: bool = False) -> None:
        with self._lock:
            if dropped:
                self.diskcache_write_dropped += 1
            elif error:
                self.diskcache_write_errors += 1
            else:
                self.diskcache_writes += 1

    def count_disk_corrupt(self) -> None:
        with self._lock:
            self.diskcache_corrupt += 1
        FLIGHT.record("diskcache.corrupt")

    def set_disk_size(self, nbytes: int, entries: int) -> None:
        with self._lock:
            self.diskcache_bytes = int(nbytes)
            self.diskcache_entries = int(entries)

    # -------------------------------------------------------- snapshot

    def count_snapshot(self, duration_ms: float,
                       error: bool = False) -> None:
        with self._lock:
            if error:
                self.snapshot_errors += 1
                return
            self.snapshots += 1
            self.snapshot_last_ts = time.time()
            self.snapshot_duration_ms = float(duration_ms)

    # ------------------------------------------------------- rehydrate

    def rehydrate_begin(self, items_total: int) -> None:
        with self._lock:
            self.rehydrate_running = True
            self.rehydrate_aborted = False
            self.rehydrate_items_total = int(items_total)
            self.rehydrate_items_done = 0

    def rehydrate_step(self, kind: str = "", nbytes: int = 0,
                       error: bool = False) -> None:
        with self._lock:
            self.rehydrate_items_done += 1
            if error:
                self.rehydrate_errors += 1
                return
            if kind == "byte":
                self.rehydrate_bytes_promoted += int(nbytes)
            elif kind == "plane":
                self.rehydrate_planes_restaged += 1
            elif kind == "executable":
                self.rehydrate_executables_loaded += 1

    def rehydrate_end(self, duration_ms: float,
                      aborted: bool = False) -> None:
        with self._lock:
            self.rehydrate_running = False
            self.rehydrate_aborted = bool(aborted)
            self.rehydrate_duration_ms = float(duration_ms)

    def rehydrate_summary(self) -> str:
        """One-line state for the /readyz annotation (rehydrate is
        best-effort: never a readiness failure, always visible)."""
        with self._lock:
            if self.rehydrate_running:
                return (f"running {self.rehydrate_items_done}"
                        f"/{self.rehydrate_items_total}")
            if self.rehydrate_aborted:
                return (f"aborted {self.rehydrate_items_done}"
                        f"/{self.rehydrate_items_total}")
            if self.rehydrate_items_total:
                return (f"done {self.rehydrate_items_done}"
                        f"/{self.rehydrate_items_total}")
        return "idle"

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        def label() -> str:
            inner = extra_labels.lstrip(",")
            return f"{{{inner}}}" if inner else ""

        lb = label()
        with self._lock:
            age_s = (time.time() - self.snapshot_last_ts
                     if self.snapshot_last_ts else 0.0)
            return [
                f"imageregion_diskcache_writes_total{lb} "
                f"{self.diskcache_writes}",
                f"imageregion_diskcache_write_errors_total{lb} "
                f"{self.diskcache_write_errors}",
                f"imageregion_diskcache_write_dropped_total{lb} "
                f"{self.diskcache_write_dropped}",
                f"imageregion_diskcache_corrupt_total{lb} "
                f"{self.diskcache_corrupt}",
                f"imageregion_diskcache_bytes{lb} "
                f"{self.diskcache_bytes}",
                f"imageregion_diskcache_entries{lb} "
                f"{self.diskcache_entries}",
                f"imageregion_warmstate_snapshots_total{lb} "
                f"{self.snapshots}",
                f"imageregion_warmstate_snapshot_errors_total{lb} "
                f"{self.snapshot_errors}",
                f"imageregion_warmstate_snapshot_age_seconds{lb} "
                f"{round(age_s, 3)}",
                f"imageregion_warmstate_snapshot_duration_ms{lb} "
                f"{round(self.snapshot_duration_ms, 3)}",
                f"imageregion_rehydrate_running{lb} "
                f"{1 if self.rehydrate_running else 0}",
                f"imageregion_rehydrate_items_total{lb} "
                f"{self.rehydrate_items_total}",
                f"imageregion_rehydrate_items_done{lb} "
                f"{self.rehydrate_items_done}",
                f"imageregion_rehydrate_errors_total{lb} "
                f"{self.rehydrate_errors}",
                f"imageregion_rehydrate_duration_ms{lb} "
                f"{round(self.rehydrate_duration_ms, 3)}",
                f"imageregion_rehydrate_bytes_promoted_total{lb} "
                f"{self.rehydrate_bytes_promoted}",
                f"imageregion_rehydrate_planes_restaged_total{lb} "
                f"{self.rehydrate_planes_restaged}",
                f"imageregion_rehydrate_executables_loaded_total{lb} "
                f"{self.rehydrate_executables_loaded}",
            ]

    def reset(self) -> None:
        with self._lock:
            self.diskcache_writes = 0
            self.diskcache_write_errors = 0
            self.diskcache_write_dropped = 0
            self.diskcache_corrupt = 0
            self.diskcache_bytes = 0
            self.diskcache_entries = 0
            self.snapshots = 0
            self.snapshot_errors = 0
            self.snapshot_last_ts = 0.0
            self.snapshot_duration_ms = 0.0
            self.rehydrate_running = False
            self.rehydrate_items_total = 0
            self.rehydrate_items_done = 0
            self.rehydrate_errors = 0
            self.rehydrate_aborted = False
            self.rehydrate_duration_ms = 0.0
            self.rehydrate_bytes_promoted = 0
            self.rehydrate_planes_restaged = 0
            self.rehydrate_executables_loaded = 0


PERSIST = Persistence()


def resilience_metric_lines(breaker=None,
                            extra_labels: str = "") -> List[str]:
    """The fault-tolerance series.  ``breaker`` is the sidecar client's
    CircuitBreaker (frontend processes only; None omits the gauge)."""
    def label(body: str = "") -> str:
        inner = body + (("," if body else "")
                        + extra_labels.lstrip(",") if extra_labels
                        else "")
        return f"{{{inner}}}" if inner else ""

    lines: List[str] = []
    if breaker is not None:
        # 0 closed / 1 half-open / 2 open (utils.transient enum order).
        lines += [
            f"imageregion_breaker_state{label()} {breaker.state}",
            f"imageregion_breaker_opens_total{label()} {breaker.opens}",
        ]
    with RESILIENCE._lock:
        shed = sorted(RESILIENCE.shed.items())
        retries = sorted(RESILIENCE.retries.items())
        deadline_cancelled = RESILIENCE.deadline_cancelled
        degraded = RESILIENCE.degraded_renders
        restarts = RESILIENCE.supervisor_restarts
    for reason, n in shed:
        body = f'reason="{reason}"'
        lines.append(f"imageregion_shed_total{label(body)} {n}")
    for op, n in retries:
        body = f'op="{op}"'
        lines.append(f"imageregion_retries_total{label(body)} {n}")
    lines += [
        f"imageregion_deadline_cancelled_total{label()} "
        f"{deadline_cancelled}",
        f"imageregion_degraded_renders_total{label()} {degraded}",
        f"imageregion_supervisor_restarts_total{label()} {restarts}",
    ]
    if not extra_labels:
        # The per-op attempts histogram composes its own labels; the
        # sidecar merge path (extra_labels) skips it rather than emit
        # label-mangled series.
        lines += RESILIENCE.attempts_hist.series(
            "imageregion_retry_attempts")
    return lines


# ------------------------------------------------------------- wire stats

class WireStats:
    """Sidecar wire transport accounting (protocol v3): vectored-flush
    coalescing, the same-host shared-memory ring, and progressive chunk
    streaming.  Thread-safe — the client and server frame writers run
    on event loops, but smoke benches read concurrently."""

    def __init__(self):
        self._lock = threading.Lock()
        # Scatter-gather flushes: one writelines + one drain each.
        self.flushes = 0
        self.frames_flushed = 0
        self.flush_bytes = 0
        # Same-host ring: bodies that rode it vs fell back to the
        # socket (exhaustion / no negotiated ring for that size class).
        self.ring_hits = 0
        self.ring_fallbacks = 0
        self.ring_bytes = 0
        # Handshakes: connections that negotiated a ring vs degraded.
        self.ring_negotiated = 0
        self.ring_declined = 0
        # Progressive streaming: responses sent as chunk frames.
        self.streams = 0
        self.chunks = 0

    def observe_flush(self, frames: int, nbytes: int) -> None:
        with self._lock:
            self.flushes += 1
            self.frames_flushed += int(frames)
            self.flush_bytes += int(nbytes)

    def count_ring(self, nbytes: int, hit: bool) -> None:
        with self._lock:
            if hit:
                self.ring_hits += 1
                self.ring_bytes += int(nbytes)
            else:
                self.ring_fallbacks += 1

    def count_negotiation(self, ring: bool) -> None:
        with self._lock:
            if ring:
                self.ring_negotiated += 1
            else:
                self.ring_declined += 1

    def count_stream(self, chunks: int) -> None:
        with self._lock:
            self.streams += 1
            self.chunks += int(chunks)

    def frames_per_flush(self) -> Optional[float]:
        """Mean frames per vectored flush — >1 under concurrent load
        means the coalescer is actually amortizing syscalls/RTTs."""
        with self._lock:
            if not self.flushes:
                return None
            return self.frames_flushed / self.flushes

    def ring_hit_rate(self) -> Optional[float]:
        """Of the bodies eligible for the ring, the fraction that rode
        it (None until anything was eligible)."""
        with self._lock:
            total = self.ring_hits + self.ring_fallbacks
            if not total:
                return None
            return self.ring_hits / total

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        def label() -> str:
            inner = extra_labels.lstrip(",")
            return f"{{{inner}}}" if inner else ""

        lb = label()
        with self._lock:
            fpf = (self.frames_flushed / self.flushes
                   if self.flushes else 0.0)
            return [
                f"imageregion_wire_flushes_total{lb} {self.flushes}",
                f"imageregion_wire_frames_total{lb} "
                f"{self.frames_flushed}",
                f"imageregion_wire_flush_bytes_total{lb} "
                f"{self.flush_bytes}",
                f"imageregion_wire_frames_per_flush{lb} "
                f"{round(fpf, 3)}",
                f"imageregion_wire_ring_hits_total{lb} "
                f"{self.ring_hits}",
                f"imageregion_wire_ring_fallbacks_total{lb} "
                f"{self.ring_fallbacks}",
                f"imageregion_wire_ring_bytes_total{lb} "
                f"{self.ring_bytes}",
                f"imageregion_wire_ring_negotiated_total{lb} "
                f"{self.ring_negotiated}",
                f"imageregion_wire_ring_declined_total{lb} "
                f"{self.ring_declined}",
                f"imageregion_wire_streams_total{lb} {self.streams}",
                f"imageregion_wire_chunks_total{lb} {self.chunks}",
            ]

    def reset(self) -> None:
        with self._lock:
            self.flushes = 0
            self.frames_flushed = 0
            self.flush_bytes = 0
            self.ring_hits = 0
            self.ring_fallbacks = 0
            self.ring_bytes = 0
            self.ring_negotiated = 0
            self.ring_declined = 0
            self.streams = 0
            self.chunks = 0


WIRE = WireStats()


def wire_metric_lines(extra_labels: str = "") -> List[str]:
    """The wire transport series; both sides of the socket emit a copy
    (the sidecar's merges with ``process="sidecar"`` labels)."""
    return WIRE.metric_lines(extra_labels)


# -------------------------------------------------------------------- fleet

class FleetStats:
    """Fleet-routing accounting (``parallel.fleet``): per-member
    routed/stolen/failed-over counters.  The ``member`` label set is
    closed by construction — member names come from config, bounded by
    ``_MAX_MEMBERS`` as a hard cardinality guard against a buggy
    caller minting names per request."""

    _MAX_MEMBERS = 64

    def __init__(self):
        self._lock = threading.Lock()
        self.routed: Dict[str, int] = {}
        self.stolen: Dict[str, int] = {}
        self.failed_over: Dict[str, int] = {}

    def _bump(self, table: Dict[str, int], member: str) -> None:
        with self._lock:
            if member not in table and len(table) >= self._MAX_MEMBERS:
                member = "_overflow"
            table[member] = table.get(member, 0) + 1

    def count_routed(self, member: str) -> None:
        self._bump(self.routed, member)

    def count_stolen(self, member: str) -> None:
        """``member`` is the STEALER: the lane that rendered skewed
        work from source bytes without adopting cache ownership."""
        self._bump(self.stolen, member)

    def count_failed_over(self, member: str) -> None:
        """``member`` is the hash-ring-next target that ADOPTED a dead
        member's shard work."""
        self._bump(self.failed_over, member)

    def totals(self) -> Dict[str, int]:
        with self._lock:
            return {
                "routed": sum(self.routed.values()),
                "stolen": sum(self.stolen.values()),
                "failed_over": sum(self.failed_over.values()),
            }

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(member: str) -> str:
            inner = f'member="{member}"' + (("," + extra) if extra
                                            else "")
            return "{" + inner + "}"

        lines: List[str] = []
        with self._lock:
            for fam, table in (
                    ("imageregion_fleet_routed_total", self.routed),
                    ("imageregion_fleet_stolen_total", self.stolen),
                    ("imageregion_fleet_failed_over_total",
                     self.failed_over)):
                for member in sorted(table):
                    lines.append(
                        f"{fam}{label(member)} {table[member]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.routed.clear()
            self.stolen.clear()
            self.failed_over.clear()


FLEET = FleetStats()


class HotkeyStats:
    """Hot-plane replication accounting (``parallel.fleet``'s
    popularity tier): promotion/demotion lifecycle counters, replica
    staging volume, the never-double-stage violation counter (held at
    0 by the bench gate), per-member balanced-read counters (closed
    label set like :class:`FleetStats`), and the hot-route /
    replica-pressure gauges the autoscaler and runbook read."""

    _MAX_MEMBERS = 64

    def __init__(self):
        self._lock = threading.Lock()
        self.promoted = 0
        self.demoted = 0
        self.staged = 0
        self.duplicate_staged = 0
        self.balanced: Dict[str, int] = {}
        self.hot_routes = 0
        self.replica_pressure = 0.0

    def count_promoted(self) -> None:
        with self._lock:
            self.promoted += 1

    def count_demoted(self) -> None:
        with self._lock:
            self.demoted += 1

    def count_staged(self, n: int = 1) -> None:
        with self._lock:
            self.staged += int(n)

    def count_duplicate_staged(self) -> None:
        with self._lock:
            self.duplicate_staged += 1

    def count_balanced(self, member: str) -> None:
        """``member`` is a NON-OWNER replica that served a balanced
        read (owner-served reads are plain routed traffic)."""
        with self._lock:
            if member not in self.balanced \
                    and len(self.balanced) >= self._MAX_MEMBERS:
                member = "_overflow"
            self.balanced[member] = self.balanced.get(member, 0) + 1

    def set_hot_routes(self, n: int) -> None:
        with self._lock:
            self.hot_routes = int(n)

    def set_pressure(self, value: float) -> None:
        with self._lock:
            self.replica_pressure = float(value)

    def totals(self) -> Dict[str, float]:
        with self._lock:
            return {
                "promoted": self.promoted,
                "demoted": self.demoted,
                "staged": self.staged,
                "duplicate_staged": self.duplicate_staged,
                "balanced": sum(self.balanced.values()),
                "hot_routes": self.hot_routes,
                "replica_pressure": self.replica_pressure,
            }

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")
        suffix = ("{" + extra + "}") if extra else ""

        def label(member: str) -> str:
            inner = f'member="{member}"' + (("," + extra) if extra
                                            else "")
            return "{" + inner + "}"

        lines: List[str] = []
        with self._lock:
            if not (self.promoted or self.demoted or self.staged
                    or self.duplicate_staged or self.balanced
                    or self.hot_routes or self.replica_pressure):
                return lines       # tier never engaged: no series
            lines.append("imageregion_hotkey_promotions_total"
                         f"{suffix} {self.promoted}")
            lines.append("imageregion_hotkey_demotions_total"
                         f"{suffix} {self.demoted}")
            lines.append("imageregion_hotkey_replica_staged_total"
                         f"{suffix} {self.staged}")
            lines.append("imageregion_hotkey_duplicate_staged_total"
                         f"{suffix} {self.duplicate_staged}")
            lines.append(f"imageregion_hotkey_hot_routes{suffix} "
                         f"{self.hot_routes}")
            lines.append("imageregion_hotkey_replica_pressure"
                         f"{suffix} {self.replica_pressure:.3f}")
            for member in sorted(self.balanced):
                lines.append("imageregion_hotkey_balanced_total"
                             f"{label(member)} "
                             f"{self.balanced[member]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.promoted = 0
            self.demoted = 0
            self.staged = 0
            self.duplicate_staged = 0
            self.balanced.clear()
            self.hot_routes = 0
            self.replica_pressure = 0.0


HOTKEY = HotkeyStats()


# -------------------------------------------------- self-preservation

class PressureStats:
    """Resource-pressure governor accounting (``server.pressure``):
    the folded pressure level, the raw per-signal readings, and the
    brownout ladder's engaged set + transition counters.  Label sets
    are closed by construction — signal names come from the sampler's
    fixed set, step names from the config-validated ladder."""

    LEVELS = ("ok", "elevated", "critical")

    def __init__(self):
        self._lock = threading.Lock()
        self.level = 0                       # index into LEVELS
        self.signals: Dict[str, float] = {}
        self.steps_engaged: Dict[str, int] = {}    # step -> 0/1
        self.step_transitions: Dict[Tuple[str, str], int] = {}
        self.level_transitions = 0

    def set_level(self, level: int) -> None:
        with self._lock:
            if level != self.level:
                self.level_transitions += 1
            self.level = level

    def set_signal(self, name: str, value: float) -> None:
        with self._lock:
            self.signals[name] = float(value)

    def set_step(self, step: str, engaged: bool) -> None:
        with self._lock:
            self.steps_engaged[step] = 1 if engaged else 0
            key = (step, "engage" if engaged else "release")
            self.step_transitions[key] = \
                self.step_transitions.get(key, 0) + 1

    def declare_steps(self, steps) -> None:
        """Pre-register the ladder so every step's gauge exists from
        scrape one (a step that never engaged must read 0, not be
        absent)."""
        with self._lock:
            for step in steps:
                self.steps_engaged.setdefault(step, 0)

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            lines = [
                f"imageregion_pressure_level{label()} {self.level}",
                f"imageregion_pressure_level_transitions_total"
                f"{label()} {self.level_transitions}",
                f"imageregion_pressure_steps_engaged{label()} "
                f"{sum(self.steps_engaged.values())}",
            ]
            for name in sorted(self.signals):
                body = 'signal="%s"' % name
                lines.append(
                    f"imageregion_pressure_signal{label(body)} "
                    f"{_fmt(self.signals[name])}")
            for step in sorted(self.steps_engaged):
                body = 'step="%s"' % step
                lines.append(
                    f"imageregion_pressure_step_engaged{label(body)} "
                    f"{self.steps_engaged[step]}")
            for (step, action) in sorted(self.step_transitions):
                body = 'step="%s",action="%s"' % (step, action)
                lines.append(
                    f"imageregion_pressure_step_transitions_total"
                    f"{label(body)} "
                    f"{self.step_transitions[(step, action)]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.level = 0
            self.signals.clear()
            self.steps_engaged.clear()
            self.step_transitions.clear()
            self.level_transitions = 0


PRESSURE = PressureStats()


class WatchdogStats:
    """Watchdog accounting (``server.watchdog``): fires by healing
    action.  The ``action`` label set is closed — actions are the
    watchdog's own fixed vocabulary (requeue-group, drop-connection,
    escalate), never caller-minted."""

    def __init__(self):
        self._lock = threading.Lock()
        self.fires: Dict[str, int] = {}

    def count_fire(self, action: str) -> None:
        with self._lock:
            self.fires[action] = self.fires.get(action, 0) + 1

    def totals(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.fires)

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")
        lines: List[str] = []
        with self._lock:
            for action in sorted(self.fires):
                inner = f'action="{action}"' + (("," + extra) if extra
                                                else "")
                lines.append(
                    f"imageregion_watchdog_fires_total{{{inner}}} "
                    f"{self.fires[action]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.fires.clear()


WATCHDOG = WatchdogStats()


class DrainStats:
    """Rolling-drain accounting (``parallel.fleet`` drains): per-member
    drain state and the handoff pre-stage counter.  Member names come
    from config (same closed set as FleetStats), bounded by the same
    hard cardinality guard."""

    _MAX_MEMBERS = 64
    STATES = ("active", "draining", "drained")

    def __init__(self):
        self._lock = threading.Lock()
        self.state: Dict[str, int] = {}      # member -> STATES index
        self.transitions: Dict[str, int] = {}
        self.prestaged_planes = 0
        self.drains_total = 0

    def set_state(self, member: str, state: str) -> None:
        idx = self.STATES.index(state)
        with self._lock:
            if member not in self.state \
                    and len(self.state) >= self._MAX_MEMBERS:
                member = "_overflow"
            if self.state.get(member) != idx:
                self.transitions[member] = \
                    self.transitions.get(member, 0) + 1
            self.state[member] = idx
            if state == "drained":
                self.drains_total += 1

    def count_prestaged(self, n: int) -> None:
        with self._lock:
            self.prestaged_planes += n

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            lines = [
                f"imageregion_drain_prestaged_planes_total{label()} "
                f"{self.prestaged_planes}",
                f"imageregion_drains_total{label()} "
                f"{self.drains_total}",
            ]
            for member in sorted(self.state):
                body = 'member="%s"' % member
                lines.append(
                    f"imageregion_drain_state{label(body)} "
                    f"{self.state[member]}")
            for member in sorted(self.transitions):
                body = 'member="%s"' % member
                lines.append(
                    f"imageregion_drain_transitions_total{label(body)} "
                    f"{self.transitions[member]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.state.clear()
            self.transitions.clear()
            self.prestaged_planes = 0
            self.drains_total = 0


DRAIN = DrainStats()


class AutoscalerStats:
    """Elastic-autoscaler accounting (``server.autoscaler``): the
    active-member gauge and floor/ceiling bounds, transitions by
    direction, and refused decisions by reason.  Both label sets are
    closed by construction — ``action`` is up/down, ``reason`` is
    ``autoscaler.BLOCKED_REASONS`` verbatim."""

    def __init__(self):
        self._lock = threading.Lock()
        self.active = 0
        self.floor = 0
        self.ceiling = 0
        self.transitions: Dict[str, int] = {}
        self.blocked: Dict[str, int] = {}

    def set_active(self, n: int) -> None:
        with self._lock:
            self.active = int(n)

    def set_bounds(self, floor: int, ceiling: int) -> None:
        with self._lock:
            self.floor = int(floor)
            self.ceiling = int(ceiling)

    def count_transition(self, action: str) -> None:
        with self._lock:
            self.transitions[action] = \
                self.transitions.get(action, 0) + 1

    def count_blocked(self, reason: str) -> None:
        with self._lock:
            self.blocked[reason] = self.blocked.get(reason, 0) + 1

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            if not (self.active or self.transitions or self.blocked):
                # Quiet until an autoscaler is live (emit-when-live,
                # the httpcache posture — keeps non-fleet expositions
                # and the reset() contract exact).
                return []
            lines = [
                f"imageregion_autoscaler_active_members{label()} "
                f"{self.active}",
                f"imageregion_autoscaler_floor{label()} {self.floor}",
                f"imageregion_autoscaler_ceiling{label()} "
                f"{self.ceiling}",
            ]
            for action in sorted(self.transitions):
                body = 'action="%s"' % action
                lines.append(
                    f"imageregion_autoscaler_transitions_total"
                    f"{label(body)} {self.transitions[action]}")
            for reason in sorted(self.blocked):
                body = 'reason="%s"' % reason
                lines.append(
                    f"imageregion_autoscaler_blocked_total"
                    f"{label(body)} {self.blocked[reason]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.active = 0
            self.floor = 0
            self.ceiling = 0
            self.transitions.clear()
            self.blocked.clear()


AUTOSCALER = AutoscalerStats()


class LoadModelStats:
    """Open-loop load-model accounting (``services.loadmodel``): how
    many arrivals the generator offered/completed per request class,
    sheds observed, and arrivals that fired behind schedule (the
    open-loop integrity counter — a generator that cannot keep its
    own schedule is measuring itself, not the service).  ``class`` is
    the closed ``loadmodel.CLASSES`` vocabulary."""

    def __init__(self):
        self._lock = threading.Lock()
        self.offered: Dict[str, int] = {}
        self.completed: Dict[str, int] = {}
        self.sheds = 0
        self.late = 0

    def count_offered(self, cls: str) -> None:
        with self._lock:
            self.offered[cls] = self.offered.get(cls, 0) + 1

    def count_completed(self, cls: str) -> None:
        with self._lock:
            self.completed[cls] = self.completed.get(cls, 0) + 1

    def count_shed(self) -> None:
        with self._lock:
            self.sheds += 1

    def count_late(self) -> None:
        with self._lock:
            self.late += 1

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            if not (self.offered or self.sheds or self.late):
                return []        # emit-when-live (bench-side family)
            lines = [
                f"imageregion_loadmodel_shed_total{label()} "
                f"{self.sheds}",
                f"imageregion_loadmodel_late_fires_total{label()} "
                f"{self.late}",
            ]
            for cls in sorted(self.offered):
                body = 'class="%s"' % cls
                lines.append(
                    f"imageregion_loadmodel_offered_total"
                    f"{label(body)} {self.offered[cls]}")
            for cls in sorted(self.completed):
                body = 'class="%s"' % cls
                lines.append(
                    f"imageregion_loadmodel_completed_total"
                    f"{label(body)} {self.completed[cls]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.offered.clear()
            self.completed.clear()
            self.sheds = 0
            self.late = 0


LOADMODEL = LoadModelStats()


class WorkloadStats:
    """Device-workloads plane accounting (PR 20): the batched
    mask/overlay rasterizer (``kind`` is the closed request vocabulary
    — which path served it), the crash-safe pyramid job subsystem
    (``action`` is the closed lifecycle vocabulary), and the z/t
    animation streamer (streams/frames/cancels plus the last stream's
    first-frame latency — the bounded-latency contract's live gauge)."""

    REQUEST_KINDS = ("mask_device", "mask_host", "overlay", "animation")
    JOB_ACTIONS = ("submitted", "resumed", "completed", "failed",
                   "cancelled", "deferred")

    def __init__(self):
        self._lock = threading.Lock()
        self.requests: Dict[str, int] = {}
        self.jobs: Dict[str, int] = {}
        self.jobs_active = 0
        self.levels_committed = 0
        self.streams = 0
        self.frames = 0
        self.stream_cancels = 0
        self.first_frame_ms: Optional[float] = None

    def count_request(self, kind: str) -> None:
        with self._lock:
            self.requests[kind] = self.requests.get(kind, 0) + 1

    def count_job(self, action: str) -> None:
        with self._lock:
            self.jobs[action] = self.jobs.get(action, 0) + 1

    def job_started(self) -> None:
        with self._lock:
            self.jobs_active += 1

    def job_finished(self) -> None:
        with self._lock:
            self.jobs_active = max(0, self.jobs_active - 1)

    def count_level_committed(self) -> None:
        with self._lock:
            self.levels_committed += 1

    def count_stream(self) -> None:
        with self._lock:
            self.streams += 1

    def count_frames(self, n: int = 1) -> None:
        with self._lock:
            self.frames += n

    def count_stream_cancelled(self) -> None:
        with self._lock:
            self.stream_cancels += 1

    def observe_first_frame_ms(self, ms: float) -> None:
        with self._lock:
            self.first_frame_ms = ms

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            if not (self.requests or self.jobs or self.jobs_active
                    or self.levels_committed or self.streams):
                return []        # emit-when-live (workloads-plane only)
            lines = []
            for kind in sorted(self.requests):
                body = 'kind="%s"' % kind
                lines.append(
                    f"imageregion_workload_requests_total"
                    f"{label(body)} {self.requests[kind]}")
            for action in sorted(self.jobs):
                body = 'action="%s"' % action
                lines.append(
                    f"imageregion_pyramid_jobs_total"
                    f"{label(body)} {self.jobs[action]}")
            lines += [
                f"imageregion_pyramid_jobs_active{label()} "
                f"{self.jobs_active}",
                f"imageregion_pyramid_levels_committed_total{label()} "
                f"{self.levels_committed}",
                f"imageregion_animation_streams_total{label()} "
                f"{self.streams}",
                f"imageregion_animation_frames_total{label()} "
                f"{self.frames}",
                f"imageregion_animation_cancelled_total{label()} "
                f"{self.stream_cancels}",
            ]
            if self.first_frame_ms is not None:
                lines.append(
                    f"imageregion_animation_first_frame_ms{label()} "
                    f"{_fmt(self.first_frame_ms)}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.requests.clear()
            self.jobs.clear()
            self.jobs_active = 0
            self.levels_committed = 0
            self.streams = 0
            self.frames = 0
            self.stream_cancels = 0
            self.first_frame_ms = None


WORKLOADS = WorkloadStats()


class FederationStats:
    """Cross-host federation accounting (``parallel.federation``): the
    agreed manifest's version + member count, join-time agreement
    outcomes, gossip-round outcomes, cross-host warm shard transfers
    (the ``shard_transfer`` wire op, both directions counted where
    they ship) and remote prestage hints fired by the shard-aware
    prefetcher.  Both label sets reuse the closed ``reason``
    vocabulary — :data:`AGREEMENT_REASONS` / :data:`GOSSIP_REASONS`
    here, never caller-minted strings."""

    AGREEMENT_REASONS = ("agreed", "pending", "stale", "split-brain",
                         "unreachable", "legacy")
    GOSSIP_REASONS = ("ok", "mismatch", "unreachable")

    def __init__(self):
        self._lock = threading.Lock()
        self.manifest_version = 0
        self.members = 0
        self.agreements: Dict[str, int] = {}
        self.gossip: Dict[str, int] = {}
        self.shard_transfers = 0
        self.transfer_bytes = 0
        self.remote_prestage = 0

    def set_manifest(self, version: int, members: int) -> None:
        with self._lock:
            self.manifest_version = int(version)
            self.members = int(members)

    def count_agreement(self, reason: str) -> None:
        if reason not in self.AGREEMENT_REASONS:
            reason = "unreachable"
        with self._lock:
            self.agreements[reason] = self.agreements.get(reason, 0) + 1

    def count_gossip(self, reason: str) -> None:
        if reason not in self.GOSSIP_REASONS:
            reason = "unreachable"
        with self._lock:
            self.gossip[reason] = self.gossip.get(reason, 0) + 1

    def count_transfer(self, nbytes: int) -> None:
        with self._lock:
            self.shard_transfers += 1
            self.transfer_bytes += int(nbytes)

    def count_remote_prestage(self, n: int = 1) -> None:
        with self._lock:
            self.remote_prestage += n

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            if not (self.manifest_version or self.agreements
                    or self.gossip or self.shard_transfers
                    or self.remote_prestage):
                # Emit-when-live (the autoscaler posture): non-federated
                # deployments keep their expositions — and the reset()
                # contract — exact.
                return []
            lines = [
                f"imageregion_federation_manifest_version{label()} "
                f"{self.manifest_version}",
                f"imageregion_federation_members{label()} "
                f"{self.members}",
                f"imageregion_federation_shard_transfers_total"
                f"{label()} {self.shard_transfers}",
                f"imageregion_federation_transfer_bytes_total"
                f"{label()} {self.transfer_bytes}",
                f"imageregion_federation_remote_prestage_total"
                f"{label()} {self.remote_prestage}",
            ]
            for reason in sorted(self.agreements):
                body = 'reason="%s"' % reason
                lines.append(
                    f"imageregion_federation_agreements_total"
                    f"{label(body)} {self.agreements[reason]}")
            for reason in sorted(self.gossip):
                body = 'reason="%s"' % reason
                lines.append(
                    f"imageregion_federation_gossip_total"
                    f"{label(body)} {self.gossip[reason]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.manifest_version = 0
            self.members = 0
            self.agreements.clear()
            self.gossip.clear()
            self.shard_transfers = 0
            self.transfer_bytes = 0
            self.remote_prestage = 0


FEDERATION = FederationStats()


class DecisionStats:
    """Exposition half of the control-plane decision ledger
    (``utils.decisions`` owns the ring + spool): counts per
    (kind, verdict) as ``imageregion_decision_total``.  BOTH label
    vocabularies are closed and owned HERE so the cardinality budget
    can bound them mechanically — the ledger imports them, callers
    never mint either string."""

    KINDS = ("autoscaler", "epoch", "manifest", "gossip",
             "drain", "undrain", "handoff", "hotkey", "quorum",
             "sentinel")
    VERDICTS = ("up", "down", "blocked", "steady",
                "installed", "pending", "promoted", "demoted",
                "agreed", "stale", "split-brain", "unreachable",
                "legacy", "ok", "mismatch", "done", "failed",
                "fenced", "restored", "drift", "recovered")

    def __init__(self):
        self._lock = threading.Lock()
        self.counts: Dict[Tuple[str, str], int] = {}

    def count(self, kind: str, verdict: str) -> None:
        if kind not in self.KINDS or verdict not in self.VERDICTS:
            return                       # ledger already warned
        with self._lock:
            key = (kind, verdict)
            self.counts[key] = self.counts.get(key, 0) + 1

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            if not self.counts:
                return []                # emit-when-live
            return [
                f"imageregion_decision_total"
                f"{label('kind=%s,verdict=%s' % (json.dumps(k), json.dumps(v)))}"
                f" {n}"
                for (k, v), n in sorted(self.counts.items())
            ]

    def reset(self) -> None:
        with self._lock:
            self.counts.clear()


DECISIONS = DecisionStats()


class FleetSloStats:
    """Fleet-level SLO burn: every federated host exports its
    ``SloEngine`` window buckets over the gossip wire
    (``SloEngine.export_buckets`` — age-keyed, since bucket indices
    are process-local monotonic) and the frontend re-anchors them here
    against its own clock, so one host's error budget burning is
    visible on the aggregating host's exposition as
    ``imageregion_fleet_slo_*`` even while the fleet-wide mean looks
    healthy.  The ``host`` label is bounded by ``_MAX_HOSTS``:
    ingests for new hosts beyond the bound are dropped (and counted)
    rather than growing the exposition — the overflow guard the
    cardinality budget relies on.  Objectives are assumed homogeneous
    across the fleet (one config rolled everywhere); the strictest
    target seen wins when they drift."""

    _MAX_HOSTS = 16

    def __init__(self):
        self._lock = threading.Lock()
        self._clock = time.monotonic
        # host -> {"t": ingest instant, "export": SloEngine export doc}
        self.hosts: Dict[str, dict] = {}
        self.dropped_hosts = 0

    def configure(self, clock=time.monotonic) -> None:
        with self._lock:
            self._clock = clock

    def ingest(self, host: str, export) -> bool:
        if not host or not isinstance(export, dict) \
                or not export.get("buckets"):
            return False
        with self._lock:
            if host not in self.hosts \
                    and len(self.hosts) >= self._MAX_HOSTS:
                self.dropped_hosts += 1
                return False
            self.hosts[host] = {"t": self._clock(),
                                "export": dict(export)}
        return True

    @staticmethod
    def _window_counts(export: dict, elapsed: float,
                       window_s: float) -> Dict[str, int]:
        out = {"ok": 0, "err": 0, "fast": 0, "slow": 0}
        bucket_s = float(export.get("bucket_s", 5.0))
        for row in export.get("buckets", ()):
            try:
                age, ok, err, fast, slow = row
            except (TypeError, ValueError):
                continue
            # ``age`` dates the bucket START at export; a bucket still
            # counts while any part of it overlaps the window.
            if float(age) + elapsed - bucket_s <= window_s:
                out["ok"] += int(ok)
                out["err"] += int(err)
                out["fast"] += int(fast)
                out["slow"] += int(slow)
        return out

    def _burns_locked(self) -> dict:
        """{"hosts": {host: {objective: {window: burn}}},
        "fleet": {objective: {window: burn}}} over live exports."""
        now = self._clock()

        def burn(bad: int, total: int, target: float) -> float:
            if total == 0 or not target:
                return 0.0
            return (bad / total) / max(1e-9, 1.0 - target)

        per_host: Dict[str, dict] = {}
        fleet_counts: Dict[Tuple[str, str], Dict[str, int]] = {}
        targets = {"availability": 0.0, "latency": 0.0}
        for host, entry in self.hosts.items():
            export = entry["export"]
            elapsed = max(0.0, now - entry["t"])
            targets["availability"] = max(
                targets["availability"],
                float(export.get("availability_target", 0.0)))
            targets["latency"] = max(
                targets["latency"],
                float(export.get("latency_target", 0.0))
                if export.get("latency_ms") else 0.0)
            host_doc: Dict[str, dict] = {}
            for window, window_s in (
                    ("fast", float(export.get("fast_window_s", 60.0))),
                    ("slow", float(export.get("slow_window_s",
                                              600.0)))):
                c = self._window_counts(export, elapsed, window_s)
                agg = fleet_counts.setdefault(
                    (window, ""), {"ok": 0, "err": 0,
                                   "fast": 0, "slow": 0})
                for k in c:
                    agg[k] += c[k]
                if export.get("availability_target"):
                    host_doc.setdefault("availability", {})[window] = \
                        burn(c["err"], c["ok"] + c["err"],
                             float(export["availability_target"]))
                if export.get("latency_ms"):
                    host_doc.setdefault("latency", {})[window] = \
                        burn(c["slow"], c["fast"] + c["slow"],
                             float(export.get("latency_target", 0.99)))
            per_host[host] = host_doc
        fleet: Dict[str, dict] = {}
        for (window, _), c in fleet_counts.items():
            if targets["availability"]:
                fleet.setdefault("availability", {})[window] = burn(
                    c["err"], c["ok"] + c["err"],
                    targets["availability"])
            if targets["latency"]:
                fleet.setdefault("latency", {})[window] = burn(
                    c["slow"], c["fast"] + c["slow"],
                    targets["latency"])
        return {"hosts": per_host, "fleet": fleet}

    def burns(self) -> dict:
        with self._lock:
            return self._burns_locked()

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            if not self.hosts and not self.dropped_hosts:
                return []                # emit-when-live
            doc = self._burns_locked()
            lines = [f"imageregion_fleet_slo_hosts{label()} "
                     f"{len(self.hosts)}"]
            if self.dropped_hosts:
                lines.append(
                    f"imageregion_fleet_slo_dropped_hosts_total"
                    f"{label()} {self.dropped_hosts}")
            for objective in sorted(doc["fleet"]):
                for window in sorted(doc["fleet"][objective]):
                    body = ('slo="%s",window="%s"'
                            % (objective, window))
                    lines.append(
                        f"imageregion_fleet_slo_burn_rate"
                        f"{label(body)} "
                        f"{round(doc['fleet'][objective][window], 4)}")
            for host in sorted(doc["hosts"]):
                for objective in sorted(doc["hosts"][host]):
                    for window in sorted(doc["hosts"][host][objective]):
                        body = ('host="%s",slo="%s",window="%s"'
                                % (host, objective, window))
                        rate = doc["hosts"][host][objective][window]
                        lines.append(
                            f"imageregion_fleet_slo_host_burn_rate"
                            f"{label(body)} {round(rate, 4)}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self._clock = time.monotonic
            self.hosts.clear()
            self.dropped_hosts = 0


FED_SLO = FleetSloStats()


class SentinelStats:
    """Exposition + fleet-merge half of the live perf-regression
    sentinel (``server.sentinel`` owns the sketches and the drift
    engine; this accumulator stays importable without the server
    stack).  Each member's engine pushes its per-tick summary here
    (``set_local``), gossip carries peer summaries in (``ingest`` —
    the ``FleetSloStats`` idiom, same ``_MAX_MEMBERS`` overflow guard
    the cardinality budget relies on), and ``merged`` answers
    ``GET /debug/sentinel`` with ONE fleet view instead of N
    incomparable ones."""

    _MAX_MEMBERS = 16

    def __init__(self):
        self._lock = threading.Lock()
        self._clock = time.monotonic
        # Freshness bound for the merged verdict: a member whose last
        # summary predates this is reported but not counted drifting
        # (a dead member must not pin the fleet red forever).
        self.stale_after_s = 120.0
        self.local: Optional[dict] = None
        # member -> {"t": ingest instant, "summary": tick summary doc}
        self.members: Dict[str, dict] = {}
        self.dropped_members = 0
        self.drifts = 0
        self.recoveries = 0
        self.bundles = 0
        self.bundle_errors = 0

    def configure(self, clock=time.monotonic) -> None:
        with self._lock:
            self._clock = clock

    # ------------------------------------------------- engine inputs

    def set_local(self, summary) -> None:
        """The local engine's latest tick summary (the doc gossip
        exports and ``merged`` folds in as this process's row)."""
        if isinstance(summary, dict):
            with self._lock:
                self.local = dict(summary)

    def count_drift(self) -> None:
        with self._lock:
            self.drifts += 1

    def count_recovery(self) -> None:
        with self._lock:
            self.recoveries += 1

    def count_bundle(self, error: bool = False) -> None:
        with self._lock:
            if error:
                self.bundle_errors += 1
            else:
                self.bundles += 1

    # --------------------------------------------------- fleet merge

    def export(self) -> Optional[dict]:
        """The local summary for the gossip wire (None while the
        engine has not ticked — peers skip on null)."""
        with self._lock:
            return dict(self.local) if self.local else None

    def ingest(self, member: str, summary) -> bool:
        if not member or not isinstance(summary, dict) \
                or not summary.get("verdict"):
            return False
        with self._lock:
            if member not in self.members \
                    and len(self.members) >= self._MAX_MEMBERS:
                self.dropped_members += 1
                return False
            self.members[member] = {"t": self._clock(),
                                    "summary": dict(summary)}
        return True

    def merged(self) -> dict:
        """Per-member rows + one fleet verdict: ``drifting`` while any
        FRESH member reports a confirmed drift."""
        with self._lock:
            now = self._clock()
            rows: Dict[str, dict] = {}
            if self.local:
                name = str(self.local.get("member") or "local")
                rows[name] = {"age_s": 0.0,
                              "summary": dict(self.local)}
            for member, entry in self.members.items():
                if member in rows:
                    continue
                rows[member] = {
                    "age_s": round(max(0.0, now - entry["t"]), 1),
                    "summary": dict(entry["summary"])}
            drifting = sorted(
                name for name, row in rows.items()
                if row["summary"].get("verdict") == "drifting"
                and row["age_s"] <= self.stale_after_s)
            return {
                "verdict": "drifting" if drifting else "ok",
                "drifting_members": drifting,
                "members": rows,
                "dropped_members": self.dropped_members,
                "drifts": self.drifts,
                "recoveries": self.recoveries,
                "bundles": self.bundles,
                "bundle_errors": self.bundle_errors,
            }

    # ----------------------------------------------------- exposition

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            if self.local is None and not self.members:
                return []                # emit-when-live
            local = self.local or {}
            drifting = 1 if local.get("verdict") == "drifting" else 0
            lines = [
                f"imageregion_sentinel_drift{label()} {drifting}",
                f"imageregion_sentinel_keys{label()} "
                f"{len(local.get('routes') or {})}",
                f"imageregion_sentinel_ticks_total{label()} "
                f"{int(local.get('ticks') or 0)}",
                f"imageregion_sentinel_observations_total{label()} "
                f"{int(local.get('observations') or 0)}",
                f"imageregion_sentinel_drifts_total{label()} "
                f"{self.drifts}",
                f"imageregion_sentinel_recoveries_total{label()} "
                f"{self.recoveries}",
                f"imageregion_sentinel_bundles_total{label()} "
                f"{self.bundles}",
                f"imageregion_sentinel_bundle_errors_total{label()} "
                f"{self.bundle_errors}",
            ]
            for route in sorted(local.get("routes") or {}):
                doc = local["routes"][route] or {}
                body = 'route="%s"' % route
                for key, family in (
                        ("p99_ms", "imageregion_sentinel_live_p99_ms"),
                        ("baseline_p99_ms",
                         "imageregion_sentinel_baseline_p99_ms")):
                    v = doc.get(key)
                    if isinstance(v, (int, float)):
                        lines.append(f"{family}{label(body)} "
                                     f"{round(float(v), 3)}")
            now = self._clock()
            for member in sorted(self.members):
                entry = self.members[member]
                if now - entry["t"] > self.stale_after_s:
                    continue
                v = (1 if entry["summary"].get("verdict") == "drifting"
                     else 0)
                lines.append(
                    f"imageregion_sentinel_member_drift"
                    f"{label('member=%s' % json.dumps(member))} {v}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self._clock = time.monotonic
            self.stale_after_s = 120.0
            self.local = None
            self.members.clear()
            self.dropped_members = 0
            self.drifts = 0
            self.recoveries = 0
            self.bundles = 0
            self.bundle_errors = 0


SENTINEL = SentinelStats()


class QuorumStats:
    """Partition-tolerance accounting: the quorum tracker's verdict
    (``parallel.federation.QuorumTracker``) and the link-partition
    fault injector (``utils.faultinject``).  Two families —
    ``imageregion_federation_quorum_*`` (am I in the majority, what
    have I refused while fenced) and ``imageregion_partition_*`` (the
    netsplit drill's injected link rules and the calls they blocked).
    Labels are closed vocabularies owned HERE: fence/restore
    transitions reuse the decision ledger's verdict strings, refusal
    actions are :data:`ACTIONS`, partition modes :data:`MODES`."""

    ACTIONS = ("adoption", "write_authority", "promotion",
               "autoscaler", "transfer", "roll")
    MODES = ("drop", "deny")

    def __init__(self):
        self._lock = threading.Lock()
        # None = no tracker installed (un-federated / quorum off):
        # emit-when-live keeps those expositions exact.
        self.quorate: Optional[bool] = None
        self.reachable_hosts = 0
        self.total_hosts = 0
        self.transitions: Dict[str, int] = {}
        self.refusals: Dict[str, int] = {}
        self.partition_rules = 0
        self.partition_blocked: Dict[str, int] = {}

    def set_quorum(self, quorate: bool, reachable: int,
                   total: int) -> None:
        with self._lock:
            self.quorate = bool(quorate)
            self.reachable_hosts = int(reachable)
            self.total_hosts = int(total)

    def count_transition(self, verdict: str) -> None:
        if verdict not in ("fenced", "restored"):
            return
        with self._lock:
            self.transitions[verdict] = \
                self.transitions.get(verdict, 0) + 1

    def count_refusal(self, action: str) -> None:
        if action not in self.ACTIONS:
            return
        with self._lock:
            self.refusals[action] = self.refusals.get(action, 0) + 1

    def set_partition_rules(self, n: int) -> None:
        with self._lock:
            self.partition_rules = int(n)

    def count_partition_blocked(self, mode: str) -> None:
        if mode not in self.MODES:
            mode = "drop"
        with self._lock:
            self.partition_blocked[mode] = \
                self.partition_blocked.get(mode, 0) + 1

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            lines: List[str] = []
            if self.quorate is not None:
                lines += [
                    f"imageregion_federation_quorum_quorate{label()} "
                    f"{int(self.quorate)}",
                    f"imageregion_federation_quorum_reachable_hosts"
                    f"{label()} {self.reachable_hosts}",
                    f"imageregion_federation_quorum_hosts{label()} "
                    f"{self.total_hosts}",
                ]
            for verdict in sorted(self.transitions):
                body = 'verdict="%s"' % verdict
                lines.append(
                    f"imageregion_federation_quorum_transitions_total"
                    f"{label(body)} {self.transitions[verdict]}")
            for action in sorted(self.refusals):
                body = 'action="%s"' % action
                lines.append(
                    f"imageregion_federation_quorum_refusals_total"
                    f"{label(body)} {self.refusals[action]}")
            if self.partition_rules or self.partition_blocked:
                lines.append(f"imageregion_partition_rules{label()} "
                             f"{self.partition_rules}")
            for mode in sorted(self.partition_blocked):
                body = 'mode="%s"' % mode
                lines.append(
                    f"imageregion_partition_blocked_total"
                    f"{label(body)} {self.partition_blocked[mode]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.quorate = None
            self.reachable_hosts = 0
            self.total_hosts = 0
            self.transitions.clear()
            self.refusals.clear()
            self.partition_rules = 0
            self.partition_blocked.clear()


QUORUM = QuorumStats()


class SessionStats:
    """Session-model accounting (``services.viewport`` +
    ``server.admission.SessionTokenBuckets``): how many distinct
    sessions the viewport tracker currently models, how many tile
    observations fed it, and LRU evictions (the bound working).  No
    per-session labels, ever — sessions are unbounded-cardinality by
    definition, so only aggregates reach the exposition."""

    def __init__(self):
        self._lock = threading.Lock()
        self.tracked = 0
        self.observations = 0
        self.evicted = 0

    def set_tracked(self, n: int) -> None:
        with self._lock:
            self.tracked = int(n)

    def count_observation(self) -> None:
        with self._lock:
            self.observations += 1

    def count_evicted(self) -> None:
        with self._lock:
            self.evicted += 1

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")
        lb = ("{" + extra + "}") if extra else ""
        with self._lock:
            return [
                f"imageregion_session_tracked{lb} {self.tracked}",
                f"imageregion_session_observations_total{lb} "
                f"{self.observations}",
                f"imageregion_session_evictions_total{lb} "
                f"{self.evicted}",
            ]

    def reset(self) -> None:
        with self._lock:
            self.tracked = 0
            self.observations = 0
            self.evicted = 0


SESSIONS = SessionStats()


class PrefetchStats:
    """Predictive-prefetch accounting (``services.prefetch``):
    predictions made, loads scheduled/staged, foreground hits on
    prefetched planes, skips by reason, and the live budget scale.
    The ``reason`` label set is closed — this module's own vocabulary
    (budget, paused), never caller-minted."""

    def __init__(self):
        self._lock = threading.Lock()
        self.predicted = 0
        self.scheduled = 0
        self.staged = 0
        self.hits = 0
        self.skipped: Dict[str, int] = {}
        self.budget_scale = 1.0

    def count_predicted(self, n: int = 1) -> None:
        with self._lock:
            self.predicted += n

    def count_scheduled(self) -> None:
        with self._lock:
            self.scheduled += 1

    def count_staged(self) -> None:
        with self._lock:
            self.staged += 1

    def count_hit(self) -> None:
        with self._lock:
            self.hits += 1

    def count_skipped(self, reason: str) -> None:
        with self._lock:
            self.skipped[reason] = self.skipped.get(reason, 0) + 1

    def set_budget(self, scale: float) -> None:
        with self._lock:
            self.budget_scale = float(scale)

    def hit_rate(self) -> Optional[float]:
        with self._lock:
            if not self.staged:
                return None
            return self.hits / self.staged

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            lines = [
                f"imageregion_prefetch_predicted_total{label()} "
                f"{self.predicted}",
                f"imageregion_prefetch_scheduled_total{label()} "
                f"{self.scheduled}",
                f"imageregion_prefetch_staged_total{label()} "
                f"{self.staged}",
                f"imageregion_prefetch_hits_total{label()} "
                f"{self.hits}",
                f"imageregion_prefetch_budget_scale{label()} "
                f"{_fmt(self.budget_scale)}",
            ]
            for reason in sorted(self.skipped):
                body = 'reason="%s"' % reason
                lines.append(
                    f"imageregion_prefetch_skipped_total{label(body)} "
                    f"{self.skipped[reason]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.predicted = 0
            self.scheduled = 0
            self.staged = 0
            self.hits = 0
            self.skipped.clear()
            self.budget_scale = 1.0


PREFETCH = PrefetchStats()


class QosStats:
    """Tiered-QoS accounting (``server.admission`` fairness sheds +
    the fleet router's weighted dequeue): sheds and dequeues by QoS
    class, and how often interactive work jumped a bulk backlog.  The
    ``class`` label is closed by construction — the two-value
    interactive/bulk vocabulary of ``pressure.is_bulk``."""

    CLASSES = ("interactive", "bulk")

    def __init__(self):
        self._lock = threading.Lock()
        self.shed: Dict[str, int] = {}
        self.dequeued: Dict[str, int] = {}
        self.jumps = 0

    def count_shed(self, cls: str) -> None:
        with self._lock:
            self.shed[cls] = self.shed.get(cls, 0) + 1

    def count_dequeued(self, cls: str) -> None:
        with self._lock:
            self.dequeued[cls] = self.dequeued.get(cls, 0) + 1

    def count_jump(self) -> None:
        with self._lock:
            self.jumps += 1

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str = "") -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return ("{" + inner + "}") if inner else ""

        with self._lock:
            lines = [
                f"imageregion_qos_interactive_jumps_total{label()} "
                f"{self.jumps}",
            ]
            for cls in sorted(self.shed):
                body = 'class="%s"' % cls
                lines.append(
                    f"imageregion_qos_shed_total{label(body)} "
                    f"{self.shed[cls]}")
            for cls in sorted(self.dequeued):
                body = 'class="%s"' % cls
                lines.append(
                    f"imageregion_qos_dequeued_total{label(body)} "
                    f"{self.dequeued[cls]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.shed.clear()
            self.dequeued.clear()
            self.jumps = 0


QOS = QosStats()


class HttpCacheStats:
    """Conditional-HTTP + peer-byte-tier accounting
    (``server.httpcache`` / ``parallel.fleet`` peer fetch): how much
    repeat-viewer traffic the edge ladder answered WITHOUT a render —
    If-None-Match arrivals, 304s and renderless HEADs at L5; probe /
    hit / fetch / fallback / put-back counters for the fleet-global
    byte tier.  No labels — the families are closed scalars."""

    def __init__(self):
        self._lock = threading.Lock()
        self.etag_requests = 0     # requests arriving with If-None-Match
        self.ims_requests = 0      # If-Modified-Since-only arrivals
        self.not_modified = 0      # 304s served (zero-work revalidation)
        self.head = 0              # HEADs served renderless
        self.peer_probes = 0       # authority byte-probe round-trips
        self.peer_hits = 0         # probes answered resident=true
        self.peer_fetches = 0      # peer bodies actually served
        self.peer_fallbacks = 0    # probe/fetch failed -> render path
        self.peer_putbacks = 0     # stolen-render write-backs shipped

    def count_etag_request(self) -> None:
        with self._lock:
            self.etag_requests += 1

    def count_ims_request(self) -> None:
        with self._lock:
            self.ims_requests += 1

    def count_not_modified(self) -> None:
        with self._lock:
            self.not_modified += 1

    def count_head(self) -> None:
        with self._lock:
            self.head += 1

    def count_peer_probe(self) -> None:
        with self._lock:
            self.peer_probes += 1

    def count_peer_hit(self) -> None:
        with self._lock:
            self.peer_hits += 1

    def count_peer_fetch(self) -> None:
        with self._lock:
            self.peer_fetches += 1

    def count_peer_fallback(self) -> None:
        with self._lock:
            self.peer_fallbacks += 1

    def count_peer_putback(self) -> None:
        with self._lock:
            self.peer_putbacks += 1

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")
        lb = ("{" + extra + "}") if extra else ""
        with self._lock:
            if not (self.etag_requests or self.ims_requests
                    or self.not_modified
                    or self.head or self.peer_probes
                    or self.peer_fetches or self.peer_fallbacks
                    or self.peer_putbacks):
                # Quiet until the ladder has seen traffic (the same
                # emit-when-live posture as the fleet totals, and what
                # keeps the reset()-contract exposition exact).
                return []
            return [
                f"imageregion_httpcache_etag_requests_total{lb} "
                f"{self.etag_requests}",
                f"imageregion_httpcache_ims_requests_total{lb} "
                f"{self.ims_requests}",
                f"imageregion_httpcache_304_total{lb} "
                f"{self.not_modified}",
                f"imageregion_httpcache_head_total{lb} {self.head}",
                f"imageregion_httpcache_peer_probes_total{lb} "
                f"{self.peer_probes}",
                f"imageregion_httpcache_peer_hits_total{lb} "
                f"{self.peer_hits}",
                f"imageregion_httpcache_peer_fetches_total{lb} "
                f"{self.peer_fetches}",
                f"imageregion_httpcache_peer_fallbacks_total{lb} "
                f"{self.peer_fallbacks}",
                f"imageregion_httpcache_peer_putbacks_total{lb} "
                f"{self.peer_putbacks}",
            ]

    def reset(self) -> None:
        with self._lock:
            self.etag_requests = 0
            self.ims_requests = 0
            self.not_modified = 0
            self.head = 0
            self.peer_probes = 0
            self.peer_hits = 0
            self.peer_fetches = 0
            self.peer_fallbacks = 0
            self.peer_putbacks = 0


HTTPCACHE = HttpCacheStats()


class ProvenanceStats:
    """Response-provenance accounting (``utils.provenance``): how many
    responses each byte-source tier answered, per serving member, plus
    the routing-flag counters.  BOTH label sets are closed: ``tier``
    is ``provenance.TIERS`` verbatim (a drifted tier string is dropped
    to ``render_cold`` before it gets here), ``member`` is the
    config-named fleet set bounded like FleetStats, and ``flag`` is
    ``provenance.FLAGS``.  Thread-safe (the access-log finisher runs
    on the event loop, smoke benches read concurrently)."""

    _MAX_MEMBERS = 64

    def __init__(self):
        self._lock = threading.Lock()
        self.by_tier_member: Dict[Tuple[str, str], int] = {}
        self.flags: Dict[str, int] = {}
        # Maintained member set: count() runs in the per-request
        # finisher, so the overflow guard must be a set hit, not a
        # key-walk per response.
        self._members: set = set()

    def count(self, record: Mapping) -> None:
        from .provenance import FLAGS, TIERS
        tier = record.get("tier")
        if tier not in TIERS:
            tier = "render_cold"
        member = str(record.get("member") or "-")
        with self._lock:
            if member not in self._members:
                if len(self._members) >= self._MAX_MEMBERS:
                    member = "_overflow"
                self._members.add(member)
            key = (tier, member)
            self.by_tier_member[key] = \
                self.by_tier_member.get(key, 0) + 1
            for flag in FLAGS:
                if record.get(flag):
                    self.flags[flag] = self.flags.get(flag, 0) + 1

    def totals(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for (tier, _member), n in self.by_tier_member.items():
                out[tier] = out.get(tier, 0) + n
            return out

    def metric_lines(self, extra_labels: str = "") -> List[str]:
        extra = extra_labels.lstrip(",")

        def label(body: str) -> str:
            inner = ",".join(p for p in (body, extra) if p)
            return "{" + inner + "}"

        with self._lock:
            lines = []
            for (tier, member) in sorted(self.by_tier_member):
                body = f'tier="{tier}",member="{member}"'
                lines.append(
                    f"imageregion_provenance_total{label(body)} "
                    f"{self.by_tier_member[(tier, member)]}")
            for flag in sorted(self.flags):
                body = f'flag="{flag}"'
                lines.append(
                    f"imageregion_provenance_flags_total{label(body)} "
                    f"{self.flags[flag]}")
        return lines

    def reset(self) -> None:
        with self._lock:
            self.by_tier_member.clear()
            self.flags.clear()
            self._members.clear()


PROVENANCE = ProvenanceStats()


def exemplars_snapshot() -> Dict[str, List[dict]]:
    """The request-duration histogram's live exemplars, per route —
    the /debug/exemplars JSON view (each entry names the most recent
    trace id + provenance tier to land in that latency bucket)."""
    return REQUEST_HIST.exemplar_docs()


def session_metric_lines(extra_labels: str = "") -> List[str]:
    """The session-serving families — ``imageregion_session_*``,
    ``imageregion_prefetch_*``, ``imageregion_qos_*`` — plus the
    open-loop load model's counters (emit-when-live: only a process
    actually replaying arrivals carries them)."""
    return (SESSIONS.metric_lines(extra_labels)
            + PREFETCH.metric_lines(extra_labels)
            + QOS.metric_lines(extra_labels)
            + LOADMODEL.metric_lines(extra_labels)
            + WORKLOADS.metric_lines(extra_labels))


def robustness_metric_lines(extra_labels: str = "") -> List[str]:
    """The self-preservation families — ``imageregion_pressure_*``,
    ``imageregion_watchdog_*``, ``imageregion_drain_*`` — plus the
    session-serving families (``imageregion_session_*`` /
    ``imageregion_prefetch_*`` / ``imageregion_qos_*``) — emitted from
    BOTH roles (the governor/watchdog run wherever they are wired;
    drains live with the fleet router; sessions/QoS at the admission
    edge)."""
    return (PRESSURE.metric_lines(extra_labels)
            + WATCHDOG.metric_lines(extra_labels)
            + DRAIN.metric_lines(extra_labels)
            + AUTOSCALER.metric_lines(extra_labels)
            + FEDERATION.metric_lines(extra_labels)
            + QUORUM.metric_lines(extra_labels)
            + DECISIONS.metric_lines(extra_labels)
            + FED_SLO.metric_lines(extra_labels)
            + session_metric_lines(extra_labels))


def fleet_metric_lines(router=None, extra_labels: str = "",
                       single_flight=None) -> List[str]:
    """The ``imageregion_fleet_*`` families: the process-global
    routed/stolen/failed-over counters plus, when a live router is
    passed, per-member depth/inflight/health gauges and the HBM
    shard-ownership count (resident planes per local member).
    ``router`` is duck-typed (``parallel.fleet.FleetRouter``) so this
    module stays importable without the fleet stack.

    ``single_flight`` is the FLEET-WIDE coalescing table (it moved
    above the router, off ``services.single_flight`` — whose emitter
    would otherwise carry these families): passing it here keeps the
    ``imageregion_singleflight_*`` series alive in fleet postures."""
    extra = extra_labels.lstrip(",")
    lines = FLEET.metric_lines(extra_labels)
    lines += HOTKEY.metric_lines(extra_labels)
    if single_flight is not None:
        lb = ("{" + extra + "}") if extra else ""
        lines += [
            f"imageregion_singleflight_hits{lb} {single_flight.hits}",
            f"imageregion_singleflight_misses{lb} "
            f"{single_flight.misses}",
            f"imageregion_singleflight_inflight{lb} "
            f"{single_flight.inflight()}",
        ]
    if router is None:
        return lines

    def label(member: str = "") -> str:
        parts = [p for p in
                 ((f'member="{member}"' if member else ""), extra) if p]
        return ("{" + ",".join(parts) + "}") if parts else ""

    lines += [
        f"imageregion_fleet_members{label()} {len(router.order)}",
        f"imageregion_fleet_members_healthy{label()} "
        f"{len(router.healthy_members())}",
    ]
    for name in router.order:
        member = router.members[name]
        lines += [
            f"imageregion_fleet_member_depth{label(name)} "
            f"{router.member_depth(name)}",
            f"imageregion_fleet_member_inflight{label(name)} "
            f"{router.member_inflight(name)}",
            f"imageregion_fleet_member_healthy{label(name)} "
            f"{1 if member.healthy else 0}",
            f"imageregion_fleet_member_planes{label(name)} "
            f"{member.resident_planes()}",
        ]
    return lines


# ---------------------------------------------------------------- readiness

class Readiness:
    """Process-wide degradation state behind ``/readyz``."""

    def __init__(self):
        self.prewarm_pending = False

    def reset(self) -> None:
        self.prewarm_pending = False


READINESS = Readiness()


# -------------------------------------------------------------- slow dumps

def dump_slow_trace(trace: Trace, total_ms: float, status: int,
                    directory: str,
                    extra: Optional[dict] = None) -> Optional[str]:
    """Write the waterfall JSON for a slow request; never raises (a
    full disk must not fail the request that just succeeded).
    ``extra`` merges top-level fields into the document (the app
    attaches the provenance record so a dumped waterfall carries its
    where-did-the-bytes-come-from verdict)."""
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"{trace.trace_id}.json")
        doc = trace.to_json(total_ms=total_ms, status=status)
        if extra:
            doc.update(extra)
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        return path
    except OSError:
        log.warning("slow-trace dump to %s failed", directory,
                    exc_info=True)
        return None


# -------------------------------------------------------------- exposition

# Metric family -> Prometheus type, for every family this service can
# emit (frontend, sidecar and combined posture).  finalize_exposition
# derives each line's family and emits the # TYPE header once.
METRIC_TYPES: Dict[str, str] = {
    "imageregion_span_count": "counter",
    "imageregion_span_mean_ms": "gauge",
    "imageregion_span_ms": "histogram",
    "imageregion_request_duration_ms": "histogram",
    "imageregion_requests_total": "counter",
    "imageregion_cache_hits": "counter",
    "imageregion_cache_misses": "counter",
    "imageregion_cache_evictions": "counter",
    "imageregion_rawcache_hits": "counter",
    "imageregion_rawcache_misses": "counter",
    "imageregion_rawcache_evictions": "counter",
    "imageregion_rawcache_bytes": "gauge",
    "imageregion_planecache_hits": "counter",
    "imageregion_planecache_misses": "counter",
    "imageregion_singleflight_hits": "counter",
    "imageregion_singleflight_misses": "counter",
    "imageregion_singleflight_inflight": "gauge",
    "imageregion_batches_dispatched": "counter",
    "imageregion_tiles_rendered": "counter",
    "imageregion_batcher_queue_depth": "gauge",
    "imageregion_pipeline_inflight": "gauge",
    "imageregion_batcher_max_batch": "gauge",
    "imageregion_batcher_queue_wait_max_ms": "gauge",
    "imageregion_compile_events_total": "counter",
    "imageregion_compile_ms_total": "counter",
    "imageregion_link_mb_s": "gauge",
    "imageregion_link_effective_mb_s": "gauge",
    "imageregion_link_fetches_total": "counter",
    "imageregion_link_fetch_bytes_total": "counter",
    "imageregion_ready": "gauge",
    "imageregion_breaker_state": "gauge",
    "imageregion_breaker_opens_total": "counter",
    "imageregion_shed_total": "counter",
    "imageregion_retries_total": "counter",
    "imageregion_retry_attempts": "histogram",
    "imageregion_deadline_cancelled_total": "counter",
    "imageregion_degraded_renders_total": "counter",
    "imageregion_supervisor_restarts_total": "counter",
    # Cost-ledger histograms (per-route attribution of where each
    # request's time and bytes went).
    "imageregion_request_cost_device_ms": "histogram",
    "imageregion_request_cost_read_ms": "histogram",
    "imageregion_request_cost_stage_ms": "histogram",
    "imageregion_request_cost_queue_ms": "histogram",
    "imageregion_request_cost_encode_ms": "histogram",
    "imageregion_request_cost_staged_kb": "histogram",
    "imageregion_request_cost_wire_kb": "histogram",
    # SLO burn rates + breach bits.
    "imageregion_slo_burn_rate": "gauge",
    "imageregion_slo_breach": "gauge",
    "imageregion_slo_breaches_total": "counter",
    # Flight-recorder ring state.
    "imageregion_flight_events": "gauge",
    "imageregion_flight_events_total": "counter",
    "imageregion_flight_dumps_total": "counter",
    # Per-ladder-shape device cost (estimated vs observed).
    "imageregion_shape_dispatches_total": "counter",
    "imageregion_shape_device_ms_total": "counter",
    "imageregion_shape_device_ms_mean": "gauge",
    "imageregion_shape_estimated_flops": "gauge",
    "imageregion_shape_estimated_bytes": "gauge",
    # Warm-state persistence tier: disk byte cache, snapshot engine,
    # boot rehydrator, serialized render executables.
    "imageregion_diskcache_writes_total": "counter",
    "imageregion_diskcache_write_errors_total": "counter",
    "imageregion_diskcache_write_dropped_total": "counter",
    "imageregion_diskcache_corrupt_total": "counter",
    "imageregion_diskcache_bytes": "gauge",
    "imageregion_diskcache_entries": "gauge",
    "imageregion_warmstate_snapshots_total": "counter",
    "imageregion_warmstate_snapshot_errors_total": "counter",
    "imageregion_warmstate_snapshot_age_seconds": "gauge",
    "imageregion_warmstate_snapshot_duration_ms": "gauge",
    "imageregion_rehydrate_running": "gauge",
    "imageregion_rehydrate_items_total": "gauge",
    "imageregion_rehydrate_items_done": "gauge",
    "imageregion_rehydrate_errors_total": "counter",
    "imageregion_rehydrate_duration_ms": "gauge",
    "imageregion_rehydrate_bytes_promoted_total": "counter",
    "imageregion_rehydrate_planes_restaged_total": "counter",
    "imageregion_rehydrate_executables_loaded_total": "counter",
    "imageregion_execcache_hits": "counter",
    "imageregion_execcache_misses": "counter",
    "imageregion_execcache_loaded_total": "counter",
    "imageregion_execcache_saved_total": "counter",
    # Data-parallel device fleet (parallel.fleet): consistent-hash
    # routing, per-member batch lanes, bounded work stealing,
    # hash-ring-next failover, HBM shard ownership.
    "imageregion_fleet_members": "gauge",
    "imageregion_fleet_members_healthy": "gauge",
    "imageregion_fleet_member_depth": "gauge",
    "imageregion_fleet_member_inflight": "gauge",
    "imageregion_fleet_member_healthy": "gauge",
    "imageregion_fleet_member_planes": "gauge",
    "imageregion_fleet_routed_total": "counter",
    "imageregion_fleet_stolen_total": "counter",
    "imageregion_fleet_failed_over_total": "counter",
    # Hot-plane replication (parallel.fleet popularity tier):
    # promotion lifecycle, replica staging, balanced reads, and the
    # replica-pressure gauge the autoscaler consumes.
    "imageregion_hotkey_promotions_total": "counter",
    "imageregion_hotkey_demotions_total": "counter",
    "imageregion_hotkey_replica_staged_total": "counter",
    "imageregion_hotkey_duplicate_staged_total": "counter",
    "imageregion_hotkey_balanced_total": "counter",
    "imageregion_hotkey_hot_routes": "gauge",
    "imageregion_hotkey_replica_pressure": "gauge",
    # Self-preservation layer (server.pressure / server.watchdog /
    # fleet drains): brownout ladder state, watchdog fires, rolling
    # drain phases.
    "imageregion_pressure_level": "gauge",
    "imageregion_pressure_level_transitions_total": "counter",
    "imageregion_pressure_signal": "gauge",
    "imageregion_pressure_steps_engaged": "gauge",
    "imageregion_pressure_step_engaged": "gauge",
    "imageregion_pressure_step_transitions_total": "counter",
    "imageregion_watchdog_fires_total": "counter",
    "imageregion_drain_state": "gauge",
    "imageregion_drain_transitions_total": "counter",
    "imageregion_drain_prestaged_planes_total": "counter",
    "imageregion_drains_total": "counter",
    # Elastic autoscaler (server.autoscaler): fleet-size controller
    # over the drain/undrain machinery.
    "imageregion_autoscaler_active_members": "gauge",
    "imageregion_autoscaler_floor": "gauge",
    "imageregion_autoscaler_ceiling": "gauge",
    "imageregion_autoscaler_transitions_total": "counter",
    "imageregion_autoscaler_blocked_total": "counter",
    # Open-loop load model (services.loadmodel): the bench-side
    # arrival generator's integrity counters (offered vs completed vs
    # shed, behind-schedule fires).
    "imageregion_loadmodel_offered_total": "counter",
    "imageregion_loadmodel_completed_total": "counter",
    "imageregion_loadmodel_shed_total": "counter",
    "imageregion_loadmodel_late_fires_total": "counter",
    # Device workloads plane (PR 20): batched mask/overlay
    # rasterization path counters, crash-safe pyramid build jobs,
    # z/t animation streams.
    "imageregion_workload_requests_total": "counter",
    "imageregion_pyramid_jobs_total": "counter",
    "imageregion_pyramid_jobs_active": "gauge",
    "imageregion_pyramid_levels_committed_total": "counter",
    "imageregion_animation_streams_total": "counter",
    "imageregion_animation_frames_total": "counter",
    "imageregion_animation_cancelled_total": "counter",
    "imageregion_animation_first_frame_ms": "gauge",
    # Cross-host fleet federation (parallel.federation): agreed
    # manifest state, join-time agreement outcomes, gossip rounds,
    # warm shard transfers over the wire, remote prestage hints.
    "imageregion_federation_manifest_version": "gauge",
    "imageregion_federation_members": "gauge",
    "imageregion_federation_agreements_total": "counter",
    "imageregion_federation_gossip_total": "counter",
    "imageregion_federation_shard_transfers_total": "counter",
    "imageregion_federation_transfer_bytes_total": "counter",
    "imageregion_federation_remote_prestage_total": "counter",
    # Partition tolerance (QuorumStats): quorum membership verdicts,
    # fence refusals, and the netsplit drill's injected link rules.
    "imageregion_federation_quorum_quorate": "gauge",
    "imageregion_federation_quorum_reachable_hosts": "gauge",
    "imageregion_federation_quorum_hosts": "gauge",
    "imageregion_federation_quorum_transitions_total": "counter",
    "imageregion_federation_quorum_refusals_total": "counter",
    "imageregion_partition_rules": "gauge",
    "imageregion_partition_blocked_total": "counter",
    # Control-plane decision ledger (utils.decisions): every
    # autoscaler / epoch / gossip / drain action as a closed
    # (kind, verdict) pair.
    "imageregion_decision_total": "counter",
    # Fleet-level SLO burn (FleetSloStats): per-host SloEngine window
    # buckets aggregated on the federation frontend.
    "imageregion_fleet_slo_hosts": "gauge",
    "imageregion_fleet_slo_dropped_hosts_total": "counter",
    "imageregion_fleet_slo_burn_rate": "gauge",
    "imageregion_fleet_slo_host_burn_rate": "gauge",
    # Live perf-regression sentinel (server.sentinel / SentinelStats):
    # drift verdicts, per-route live-vs-baseline p99, incident-bundle
    # captures, per-member fleet verdicts off the gossip merge.
    "imageregion_sentinel_drift": "gauge",
    "imageregion_sentinel_keys": "gauge",
    "imageregion_sentinel_ticks_total": "counter",
    "imageregion_sentinel_observations_total": "counter",
    "imageregion_sentinel_drifts_total": "counter",
    "imageregion_sentinel_recoveries_total": "counter",
    "imageregion_sentinel_bundles_total": "counter",
    "imageregion_sentinel_bundle_errors_total": "counter",
    "imageregion_sentinel_live_p99_ms": "gauge",
    "imageregion_sentinel_baseline_p99_ms": "gauge",
    "imageregion_sentinel_member_drift": "gauge",
    # Session-aware serving (services.viewport / services.prefetch /
    # server.admission token buckets / fleet QoS dequeue).
    "imageregion_session_tracked": "gauge",
    "imageregion_session_observations_total": "counter",
    "imageregion_session_evictions_total": "counter",
    "imageregion_prefetch_predicted_total": "counter",
    "imageregion_prefetch_scheduled_total": "counter",
    "imageregion_prefetch_staged_total": "counter",
    "imageregion_prefetch_hits_total": "counter",
    "imageregion_prefetch_skipped_total": "counter",
    "imageregion_prefetch_budget_scale": "gauge",
    "imageregion_qos_shed_total": "counter",
    "imageregion_qos_dequeued_total": "counter",
    "imageregion_qos_interactive_jumps_total": "counter",
    # Wire transport (protocol v3, WireStats): vectored-flush
    # coalescing, shm-ring traffic, chunk streaming.  Registered here
    # so the families carry real TYPE headers and pass the committed
    # cardinality budget (scripts/metrics_lint.py) — they were
    # exposition-only ("untyped") before the budget existed.
    "imageregion_wire_flushes_total": "counter",
    "imageregion_wire_frames_total": "counter",
    "imageregion_wire_flush_bytes_total": "counter",
    "imageregion_wire_frames_per_flush": "gauge",
    "imageregion_wire_ring_hits_total": "counter",
    "imageregion_wire_ring_fallbacks_total": "counter",
    "imageregion_wire_ring_bytes_total": "counter",
    "imageregion_wire_ring_negotiated_total": "counter",
    "imageregion_wire_ring_declined_total": "counter",
    "imageregion_wire_streams_total": "counter",
    "imageregion_wire_chunks_total": "counter",
    # Conditional HTTP + fleet-global byte tier (server.httpcache /
    # parallel.fleet peer fetch): the edge offload ladder's counters.
    "imageregion_httpcache_etag_requests_total": "counter",
    "imageregion_httpcache_304_total": "counter",
    "imageregion_httpcache_head_total": "counter",
    "imageregion_httpcache_peer_probes_total": "counter",
    "imageregion_httpcache_peer_hits_total": "counter",
    "imageregion_httpcache_peer_fetches_total": "counter",
    "imageregion_httpcache_peer_fallbacks_total": "counter",
    "imageregion_httpcache_peer_putbacks_total": "counter",
    # Response provenance (utils.provenance): which byte-source tier
    # answered, per serving member, plus routing flags.
    "imageregion_provenance_total": "counter",
    "imageregion_provenance_flags_total": "counter",
    # Conditional HTTP, Last-Modified leg: If-Modified-Since-only
    # revalidations (the ETag path keeps its own counters).
    "imageregion_httpcache_ims_requests_total": "counter",
}

# Terse HELP strings for the families whose meaning is not obvious
# from the name; every family gets a HELP line (fallback text) so the
# exposition lint can hold "HELP exactly once per family" everywhere.
METRIC_HELP: Dict[str, str] = {
    "imageregion_federation_manifest_version":
        "Shard epoch of the agreed fleet manifest",
    "imageregion_federation_agreements_total":
        "Join-time manifest agreement outcomes by reason",
    "imageregion_federation_gossip_total":
        "Membership gossip round outcomes by reason",
    "imageregion_federation_shard_transfers_total":
        "Warm HBM planes shipped cross-host over shard_transfer",
    "imageregion_federation_remote_prestage_total":
        "Predicted-plane prestage hints sent to remote owners",
    "imageregion_federation_quorum_quorate":
        "1 while this host can gossip with a strict majority of "
        "manifest hosts, 0 while fenced",
    "imageregion_federation_quorum_reachable_hosts":
        "Manifest hosts (self included) heard from within "
        "suspect-after-s",
    "imageregion_federation_quorum_transitions_total":
        "Quorum fence/restore transitions by verdict",
    "imageregion_federation_quorum_refusals_total":
        "State-changing actions refused while fenced, by action",
    "imageregion_partition_rules":
        "Injected link-partition rules active in this process",
    "imageregion_partition_blocked_total":
        "Sidecar calls blocked by an injected link partition, by mode",
    "imageregion_decision_total":
        "Control-plane decision-ledger records by kind and verdict",
    "imageregion_fleet_slo_hosts":
        "Hosts currently contributing SLO window buckets to the "
        "fleet burn",
    "imageregion_fleet_slo_dropped_hosts_total":
        "SLO bucket ingests dropped by the host-cardinality bound",
    "imageregion_fleet_slo_burn_rate":
        "Fleet-aggregated error-budget burn per objective and window",
    "imageregion_fleet_slo_host_burn_rate":
        "Per-host error-budget burn per objective and window",
    "imageregion_sentinel_drift":
        "1 while the local perf sentinel holds a confirmed drift "
        "verdict",
    "imageregion_sentinel_keys":
        "Route classes the sentinel currently tracks quantiles for",
    "imageregion_sentinel_ticks_total":
        "Drift-evaluation windows the local sentinel has closed",
    "imageregion_sentinel_observations_total":
        "Requests the local sentinel has sketched",
    "imageregion_sentinel_drifts_total":
        "Per-key drift confirmations (confirm-ticks consecutive "
        "breaching windows)",
    "imageregion_sentinel_recoveries_total":
        "Per-key drift recoveries (recover-ticks consecutive clean "
        "windows)",
    "imageregion_sentinel_live_p99_ms":
        "Live windowed p99 latency per route class (sketch estimate)",
    "imageregion_sentinel_baseline_p99_ms":
        "Self-learned rolling-baseline p99 per route class",
    "imageregion_sentinel_member_drift":
        "Per-member drift verdict off the gossip merge (1 = drifting)",
    "imageregion_sentinel_bundles_total":
        "Forensic incident bundles written on confirmed drift",
    "imageregion_sentinel_bundle_errors_total":
        "Incident-bundle captures that failed (drift verdict stands)",
    "imageregion_request_cost_device_ms":
        "Per-request device-execute ms (pro-rata from batch group)",
    "imageregion_request_cost_read_ms":
        "Per-request cold pixel-store read + staging ms",
    "imageregion_request_cost_stage_ms":
        "Per-request host->HBM staging ms (pro-rata)",
    "imageregion_request_cost_queue_ms":
        "Per-request batcher queue wait ms",
    "imageregion_request_cost_encode_ms":
        "Per-request host encode ms",
    "imageregion_request_cost_staged_kb":
        "Per-request HBM bytes staged (KB, pro-rata)",
    "imageregion_request_cost_wire_kb":
        "Per-request response bytes (KB)",
    "imageregion_slo_burn_rate":
        "Error-budget burn rate per objective and window",
    "imageregion_slo_breach":
        "1 while the objective is in multi-window breach",
    "imageregion_flight_events":
        "Events currently held in the flight-recorder ring",
    "imageregion_shape_estimated_flops":
        "XLA cost_analysis flops estimate of the shape's program",
    "imageregion_batcher_queue_wait_max_ms":
        "High-water dispatched queue wait (cancelled waits excluded)",
    "imageregion_diskcache_corrupt_total":
        "Disk byte-cache entries rejected by checksum/format checks",
    "imageregion_warmstate_snapshot_age_seconds":
        "Seconds since the last warm-state manifest write (0 = never)",
    "imageregion_rehydrate_running":
        "1 while the boot rehydrator is replaying the warm-state "
        "manifest",
    "imageregion_rehydrate_bytes_promoted_total":
        "Disk byte-cache bytes promoted to the memory tier at boot",
    "imageregion_execcache_loaded_total":
        "Serialized render executables deserialized from disk",
    "imageregion_fleet_member_planes":
        "HBM-resident plane entries owned by the member (shard size)",
    "imageregion_fleet_stolen_total":
        "Renders the member stole from a backlogged peer (no cache "
        "adoption)",
    "imageregion_fleet_failed_over_total":
        "Dead-member shard work adopted hash-ring-next by the member",
    "imageregion_pressure_level":
        "Folded resource-pressure level (0 ok, 1 elevated, 2 critical)",
    "imageregion_pressure_signal":
        "Raw pressure-signal reading (fraction of budget, or raw "
        "depth/ms)",
    "imageregion_pressure_steps_engaged":
        "Brownout ladder steps currently engaged (prefix of the "
        "configured ladder)",
    "imageregion_pressure_step_engaged":
        "1 while the named ladder step is engaged",
    "imageregion_pressure_step_transitions_total":
        "Ladder step engage/release transitions",
    "imageregion_watchdog_fires_total":
        "Watchdog healings by action (requeue-group, drop-connection, "
        "escalate)",
    "imageregion_drain_state":
        "Fleet-member drain state (0 active, 1 draining, 2 drained)",
    "imageregion_drain_prestaged_planes_total":
        "Handoff planes pre-staged WARM onto ring successors by drains",
    "imageregion_session_tracked":
        "Distinct sessions currently modeled by the viewport tracker",
    "imageregion_session_evictions_total":
        "Session states evicted by the viewport tracker's LRU bound",
    "imageregion_prefetch_predicted_total":
        "Tiles predicted from session pan/zoom trajectories",
    "imageregion_prefetch_staged_total":
        "Predicted planes actually staged into an HBM tier",
    "imageregion_prefetch_hits_total":
        "Foreground requests that found their plane prefetched",
    "imageregion_prefetch_skipped_total":
        "Prefetch candidates skipped (budget exhausted or paused)",
    "imageregion_prefetch_budget_scale":
        "Live prefetch budget scale (1 full, 0 paused by the ladder)",
    "imageregion_qos_shed_total":
        "Per-session fairness sheds by QoS class (503 + Retry-After)",
    "imageregion_qos_dequeued_total":
        "Fleet-router dequeues by QoS class (weighted two-class queue)",
    "imageregion_qos_interactive_jumps_total":
        "Interactive dequeues that jumped a waiting bulk backlog",
    "imageregion_httpcache_304_total":
        "If-None-Match revalidations answered 304 with zero render/"
        "admission/token work",
    "imageregion_httpcache_head_total":
        "HEAD requests answered headers-only without a render",
    "imageregion_httpcache_peer_hits_total":
        "Authority byte-probes answered resident (peer has the bytes)",
    "imageregion_httpcache_peer_fetches_total":
        "Renders avoided by fetching bytes from a fleet peer's tier",
    "imageregion_httpcache_peer_fallbacks_total":
        "Peer probe/fetch failures that fell back to the render path",
    "imageregion_httpcache_peer_putbacks_total":
        "Stolen-render bytes written back to the shard authority",
    "imageregion_provenance_total":
        "Responses by byte-source tier and serving member "
        "(utils.provenance closed vocabulary)",
    "imageregion_provenance_flags_total":
        "Responses carrying a routing flag (stolen / failed_over / "
        "drain_rehomed / coalesced / quality_capped)",
    "imageregion_httpcache_ims_requests_total":
        "If-Modified-Since-only revalidation arrivals (ETag absent)",
    "imageregion_autoscaler_active_members":
        "Fleet members currently accepting routes (not draining)",
    "imageregion_autoscaler_floor":
        "Autoscaler hard minimum of non-draining members",
    "imageregion_autoscaler_ceiling":
        "Autoscaler maximum of active members (pre-provisioned set)",
    "imageregion_autoscaler_transitions_total":
        "Autoscaler scale transitions by direction (up = undrain "
        "with pre-stage-back, down = drain with warm handoff)",
    "imageregion_autoscaler_blocked_total":
        "Autoscaler decisions refused by reason (cooldown, floor, "
        "ceiling, busy, no-member)",
    "imageregion_loadmodel_offered_total":
        "Open-loop arrivals fired on schedule, by request class",
    "imageregion_loadmodel_completed_total":
        "Open-loop arrivals served, by request class",
    "imageregion_loadmodel_shed_total":
        "Open-loop arrivals refused with 503 + Retry-After",
    "imageregion_loadmodel_late_fires_total":
        "Arrivals fired behind schedule (open-loop integrity: the "
        "generator, not the service, fell behind)",
    "imageregion_workload_requests_total":
        "Device-workloads requests by kind (mask_device/mask_host = "
        "which rasterizer served the mask; overlay; animation)",
    "imageregion_pyramid_jobs_total":
        "Pyramid build job lifecycle transitions by action "
        "(submitted, resumed, completed, failed, cancelled, deferred)",
    "imageregion_pyramid_jobs_active":
        "Pyramid build jobs currently running or deferred",
    "imageregion_pyramid_levels_committed_total":
        "Pyramid levels atomically committed (tmp-dir os.replace)",
    "imageregion_animation_streams_total":
        "z/t animation streams started",
    "imageregion_animation_frames_total":
        "Animation frames written to clients",
    "imageregion_animation_cancelled_total":
        "Animation streams cancelled mid-flight (client disconnect "
        "or deadline) with remaining device work cancelled",
    "imageregion_animation_first_frame_ms":
        "Last animation stream's first-frame latency (the bounded "
        "first-frame-out contract's live gauge)",
    "imageregion_hotkey_promotions_total":
        "Routes promoted to an R>1 replica set (heat past threshold)",
    "imageregion_hotkey_demotions_total":
        "Promoted routes demoted back to R=1 (heat decayed)",
    "imageregion_hotkey_replica_staged_total":
        "Plane entries staged onto replicas at promotion "
        "(digest-deduped; residency probe hits count too)",
    "imageregion_hotkey_duplicate_staged_total":
        "Replica stagings that would have double-staged one "
        "(route, replica) pair in one epoch — a bug counter, held 0",
    "imageregion_hotkey_balanced_total":
        "Reads served by a NON-OWNER replica via least-queued "
        "balancing, by member",
    "imageregion_hotkey_hot_routes":
        "Routes currently holding an R>1 replica set",
    "imageregion_hotkey_replica_pressure":
        "Hottest promoted route's heat over the promotion threshold "
        "(>= 1: one plane is outrunning one member — scale-up signal)",
}

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_of(line: str) -> str:
    name = line.split("{", 1)[0].split(" ", 1)[0]
    for suffix in _HIST_SUFFIXES:
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if METRIC_TYPES.get(base) == "histogram":
                return base
    return name


def finalize_exposition(lines: List[str],
                        openmetrics: bool = False) -> str:
    """Order series by family (first-seen), emit one ``# TYPE`` header
    per family, pass comments through.  The single formatter shared by
    the app's ``/metrics`` and the sidecar merge path, so TYPE headers
    can never duplicate across the process boundary.

    ``openmetrics=True`` produces a body a STRICT OpenMetrics parser
    accepts (the negotiated exposition that carries exemplars — one
    illegal line would fail the whole scrape): free-form comments are
    dropped (only HELP/TYPE/UNIT/EOF may follow ``#``), ``untyped``
    maps to OM's ``unknown``, and counter metadata follows the OM
    naming rule — families ending ``_total`` declare HELP/TYPE under
    the suffix-less name, counters NOT ending ``_total`` (legacy
    names) degrade to ``unknown`` rather than violate the grammar.
    The caller appends the ``# EOF`` terminator."""
    families: Dict[str, List[str]] = {}
    order: List[str] = []
    comments: List[str] = []
    for line in lines:
        line = line.rstrip("\n")
        if not line:
            continue
        if line.startswith("#"):
            # TYPE and HELP are the finalizer's to emit (exactly once
            # per family); merged inputs must not smuggle duplicates.
            if not line.startswith(("# TYPE", "# HELP")):
                comments.append(line)
            continue
        fam = _family_of(line)
        if fam not in families:
            families[fam] = []
            order.append(fam)
        families[fam].append(line)
    present = set(order)
    out: List[str] = []
    for fam in order:
        mtype = METRIC_TYPES.get(fam, "untyped")
        help_text = METRIC_HELP.get(fam, fam.replace("_", " "))
        meta_name = fam
        if openmetrics:
            if mtype == "counter":
                base = fam[: -len("_total")] \
                    if fam.endswith("_total") else None
                if base and base not in present:
                    meta_name = base
                else:
                    # Legacy counter name (no _total suffix), or the
                    # suffix-less name is ITSELF a present family
                    # (imageregion_flight_events_total vs the
                    # ..._events gauge): duplicate metadata would
                    # fail the strict parser — degrade to unknown.
                    mtype = "unknown"
            elif mtype == "untyped":
                mtype = "unknown"
        out.append(f"# HELP {meta_name} {help_text}")
        out.append(f"# TYPE {meta_name} {mtype}")
        out += families[fam]
    if not openmetrics:
        out += comments
    return "\n".join(out) + "\n"


def request_metric_lines(exemplars: bool = False) -> List[str]:
    """The frontend-local request series (histogram + totals), the
    cost-ledger histograms, the SLO burn gauges and the local
    flight-recorder ring state.  ``exemplars=True`` adds the
    OpenMetrics exemplar tails to the request-duration buckets — ONLY
    for scrapes that negotiated ``application/openmetrics-text`` (the
    classic text parser rejects the syntax)."""
    lines = REQUEST_HIST.series("imageregion_request_duration_ms",
                                exemplars=exemplars)
    with _REQ_LOCK:
        totals = sorted(_REQ_TOTALS.items())
    for (route, status), n in totals:
        lines.append(f'imageregion_requests_total{{route="{route}",'
                     f'status="{status}"}} {n}')
    lines += cost_metric_lines()
    lines += HTTPCACHE.metric_lines()
    lines += PROVENANCE.metric_lines()
    lines += SLO.metric_lines()
    lines += SENTINEL.metric_lines()
    lines += [
        f"imageregion_flight_events {len(FLIGHT)}",
        f"imageregion_flight_events_total {FLIGHT.events_total}",
        f"imageregion_flight_dumps_total {FLIGHT.dumps_written}",
    ]
    return lines


def device_metric_lines(services, extra_labels: str = "") -> List[str]:
    """Series owned by a device-side process (combined app or sidecar):
    caches, raw cache, batcher gauges, compile events, link health.

    ``services`` is duck-typed (``server.handler.ImageRegionServices``)
    so this module stays importable without the server stack;
    ``extra_labels`` is appended inside every label brace (the
    sidecar's ``process="sidecar"``).
    """
    def label(body: str = "") -> str:
        inner = body + (("," if body else "")
                        + extra_labels.lstrip(",") if extra_labels
                        else "")
        return f"{{{inner}}}" if inner else ""

    lines: List[str] = []
    for cache_name in ("image_region", "pixels_metadata", "shape_mask"):
        stack = getattr(getattr(services, "caches", None), cache_name,
                        None)
        for i, tier in enumerate(getattr(stack, "tiers", ())):
            hits = getattr(tier, "hits", None)
            misses = getattr(tier, "misses", None)
            if hits is None:
                continue
            lb = label(f'cache="{cache_name}",tier="{i}"')
            lines += [
                f"imageregion_cache_hits{lb} {hits}",
                f"imageregion_cache_misses{lb} {misses}",
            ]
            evictions = getattr(tier, "evictions", None)
            if evictions is not None:
                lines.append(
                    f"imageregion_cache_evictions{lb} {evictions}")
    raw_cache = getattr(services, "raw_cache", None)
    if raw_cache is not None:
        lb = label()
        lines += [
            f"imageregion_rawcache_hits{lb} {raw_cache.hits}",
            f"imageregion_rawcache_misses{lb} {raw_cache.misses}",
            f"imageregion_rawcache_bytes{lb} {raw_cache.size_bytes}",
        ]
        if hasattr(raw_cache, "evictions"):
            lines.append(f"imageregion_rawcache_evictions{lb} "
                         f"{raw_cache.evictions}")
        if hasattr(raw_cache, "plane_hits"):
            # Content-digest staging skips: uploads the plane cache
            # saved (hits) vs paid (misses) — wire probes included.
            lines += [
                f"imageregion_planecache_hits{lb} "
                f"{raw_cache.plane_hits}",
                f"imageregion_planecache_misses{lb} "
                f"{raw_cache.plane_misses}",
            ]
    single_flight = getattr(services, "single_flight", None)
    if single_flight is not None:
        lb = label()
        lines += [
            f"imageregion_singleflight_hits{lb} {single_flight.hits}",
            f"imageregion_singleflight_misses{lb} "
            f"{single_flight.misses}",
            f"imageregion_singleflight_inflight{lb} "
            f"{single_flight.inflight()}",
        ]
    renderer = getattr(services, "renderer", None)
    if hasattr(renderer, "batches_dispatched"):
        lb = label()
        lines += [
            f"imageregion_batches_dispatched{lb} "
            f"{renderer.batches_dispatched}",
            f"imageregion_tiles_rendered{lb} "
            f"{renderer.tiles_rendered}",
        ]
    if hasattr(renderer, "queue_depth"):
        lb = label()
        lines += [
            f"imageregion_batcher_queue_depth{lb} "
            f"{renderer.queue_depth()}",
            f"imageregion_pipeline_inflight{lb} "
            f"{renderer.inflight()}",
            f"imageregion_batcher_max_batch{lb} {renderer.max_batch}",
        ]
        if hasattr(renderer, "queue_wait_max_ms"):
            # High-water queue wait: the stragglers a mean hides and a
            # p50 cannot see at all.
            lines.append(f"imageregion_batcher_queue_wait_max_ms{lb} "
                         f"{round(renderer.queue_wait_max_ms, 3)}")
    lb = label()
    lines += [
        f"imageregion_compile_events_total{lb} {COMPILE.events}",
        f"imageregion_compile_ms_total{lb} "
        f"{round(COMPILE.total_ms, 3)}",
        f"imageregion_link_fetches_total{lb} {LINK.fetches}",
        f"imageregion_link_fetch_bytes_total{lb} {LINK.bytes_total}",
    ]
    # Per-ladder-shape estimated vs observed device cost (the batcher
    # records both; cardinality is bounded by the bucket/batch ladder).
    lines += SHAPE_COSTS.metric_lines(extra_labels)
    # Warm-state persistence tier (disk byte cache, snapshot engine,
    # boot rehydrator) — device-side state, merged like the rest.
    lines += PERSIST.metric_lines(extra_labels)
    exec_cache = getattr(getattr(services, "renderer", None),
                         "exec_cache", None)
    if exec_cache is not None:
        lines += [
            f"imageregion_execcache_hits{lb} {exec_cache.hits}",
            f"imageregion_execcache_misses{lb} {exec_cache.misses}",
            f"imageregion_execcache_loaded_total{lb} "
            f"{exec_cache.loaded}",
            f"imageregion_execcache_saved_total{lb} "
            f"{exec_cache.saved}",
        ]
    if extra_labels:
        # The sidecar's flight-recorder ring, labelled so the
        # frontend's merged exposition keeps both processes' series
        # distinct.  Combined/frontend processes emit their own copy
        # unlabelled via request_metric_lines.
        lines += [
            f"imageregion_flight_events{lb} {len(FLIGHT)}",
            f"imageregion_flight_events_total{lb} "
            f"{FLIGHT.events_total}",
            f"imageregion_flight_dumps_total{lb} "
            f"{FLIGHT.dumps_written}",
        ]
    if LINK.fetches:
        # 0.0 until a bandwidth-class fetch has been rated (small
        # fetches are latency-dominated and carry no rate signal).
        lines += [
            f"imageregion_link_mb_s{lb} "
            f"{round(LINK.ewma_mb_s or 0.0, 3)}",
            f"imageregion_link_effective_mb_s{lb} "
            f"{round(LINK.effective_mb_s or 0.0, 3)}",
        ]
    return lines


def reset() -> None:
    """Test isolation: clear every process-global accumulator —
    repeated in-process test apps must not leak counts (or SLO breach
    state, or flight events) across tests."""
    TRACES.reset()
    REQUEST_HIST.reset()
    with _REQ_LOCK:
        _REQ_TOTALS.clear()
    LINK.reset()
    COMPILE.reset()
    READINESS.reset()
    RESILIENCE.reset()
    for hist in COST_HISTS.values():
        hist.reset()
    COST_TOPK.reset()
    FLIGHT.reset()
    SLO.reset()
    SHAPE_COSTS.reset()
    PERSIST.reset()
    WIRE.reset()
    FLEET.reset()
    HOTKEY.reset()
    PRESSURE.reset()
    WATCHDOG.reset()
    DRAIN.reset()
    AUTOSCALER.reset()
    LOADMODEL.reset()
    WORKLOADS.reset()
    FEDERATION.reset()
    QUORUM.reset()
    DECISIONS.reset()
    FED_SLO.reset()
    SENTINEL.reset()
    SESSIONS.reset()
    PREFETCH.reset()
    QOS.reset()
    HTTPCACHE.reset()
    PROVENANCE.reset()
    # The decision ledger lives in utils.decisions (which imports this
    # module); reset it from here so ONE reset() call keeps the whole
    # forensics plane test-isolated.  Lazy import breaks the cycle.
    from . import decisions as _decisions
    _decisions.LEDGER.reset()
