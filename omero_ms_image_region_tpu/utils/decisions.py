"""Control-plane decision ledger: what the fleet DECIDED, and why.

The forensics plane (traces, flight ring, /debug/costs, SLO burn)
answers "what happened to this request"; this module answers "why did
the fleet do X at 14:02" — the question an epoch roll, a gossip fork,
or a 3 a.m. scale-down raises and nothing else records.  Every
control-plane action lands here as one closed-vocabulary record:

* ``autoscaler`` — one record per tick VERDICT transition (signal
  snapshot -> diurnal demand prediction -> want -> ``up`` / ``down`` /
  ``blocked`` / ``steady``), with the MEASURED outcome attached
  ``outcome-horizon-ticks`` ticks later (did the queue actually fall?);
* ``epoch`` — manifest install / pending-roll phases
  (``parallel.federation.install`` / ``set_pending``);
* ``manifest`` — per-member digest agreement verdicts (the
  ``FederationStats.AGREEMENT_REASONS`` vocabulary);
* ``gossip`` — per-peer convergence transitions (``ok`` /
  ``mismatch`` / ``unreachable``);
* ``drain`` / ``undrain`` / ``handoff`` — member lifecycle moves and
  cross-host shard handoffs.

Both vocabularies are owned by ``telemetry.DecisionStats`` (KINDS /
VERDICTS) so the cardinality budget bounds the
``imageregion_decision_total{kind,verdict}`` family mechanically.
Each record also fires a ``decision.<kind>`` flight event — the black
box and the ledger tell one story.

Storage is the flight-recorder shape: an append-only bounded ring
(``/debug/decisions`` snapshots it; the federated frontend merges
every host's ring ts-sorted) plus an optional JSONL spool
(``decisions.jsonl``, one-file rotation) for post-mortems that outlive
the ring.  Recording must never fail the control plane: bad vocab is
dropped with a warning, spool errors are counted and swallowed.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from . import telemetry

log = logging.getLogger("omero_ms_image_region_tpu.decisions")

KINDS = telemetry.DecisionStats.KINDS
VERDICTS = telemetry.DecisionStats.VERDICTS

# One rotation (decisions.jsonl -> decisions.jsonl.1) keeps the spool
# bounded without a compaction thread.
_SPOOL_MAX_BYTES = 4 * 1024 * 1024
_SPOOL_NAME = "decisions.jsonl"


class DecisionLedger:
    """Bounded ring + JSONL spool of control-plane decision records."""

    def __init__(self, ring_size: int = 256, spool_dir: str = "",
                 outcome_horizon_ticks: int = 3, host: str = ""):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(16, int(ring_size)))
        self._seq = 0
        self.records_total = 0
        self.spool_dir = spool_dir
        self.spool_errors = 0
        self.outcome_horizon_ticks = max(1, int(outcome_horizon_ticks))
        self.host = host

    def configure(self, ring_size: Optional[int] = None,
                  spool_dir: Optional[str] = None,
                  outcome_horizon_ticks: Optional[int] = None,
                  host: Optional[str] = None) -> None:
        """App-startup wiring (``decisions:`` config block).  Ring
        contents survive a re-size (tail-truncated to the new bound)
        so a mid-life reconfigure never erases the recent story."""
        with self._lock:
            if ring_size is not None:
                self._ring = collections.deque(
                    self._ring, maxlen=max(16, int(ring_size)))
            if spool_dir is not None:
                self.spool_dir = spool_dir
            if outcome_horizon_ticks is not None:
                self.outcome_horizon_ticks = max(
                    1, int(outcome_horizon_ticks))
            if host is not None:
                self.host = host

    # ------------------------------------------------------------ record

    def record(self, kind: str, verdict: str, member: str = "",
               detail: Optional[dict] = None) -> int:
        """Append one decision record; returns its ``seq`` (the handle
        ``resolve`` attaches the measured outcome to), or -1 when the
        vocabulary rejected it.  Never raises."""
        if kind not in KINDS or verdict not in VERDICTS:
            log.warning("decision dropped: kind=%r verdict=%r not in "
                        "the closed vocabulary", kind, verdict)
            return -1
        with self._lock:
            self._seq += 1
            seq = self._seq
            rec: Dict[str, object] = {
                "seq": seq, "ts": time.time(),
                "kind": kind, "verdict": verdict,
            }
            if self.host:
                rec["host"] = self.host
            if member:
                rec["member"] = member
            if detail:
                rec["detail"] = dict(detail)
            self._ring.append(rec)
            self.records_total += 1
        telemetry.DECISIONS.count(kind, verdict)
        fields = {"verdict": verdict, "seq": seq}
        if member:
            # Only stamp when we have one: an empty member would mask
            # the flight recorder's own process-identity stamp.
            fields["member"] = member
        telemetry.FLIGHT.record(f"decision.{kind}", **fields)
        self._spool(rec)
        return seq

    def resolve(self, seq: int, outcome: dict) -> bool:
        """Attach the measured outcome to a prior record (autoscaler
        verdicts, N ticks later).  True when the record was still in
        the ring; the spool gets its own outcome line either way, so a
        post-mortem can join them even after the ring moved on."""
        found = False
        with self._lock:
            for rec in reversed(self._ring):
                if rec.get("seq") == seq:
                    rec["outcome"] = dict(outcome)
                    found = True
                    break
        self._spool({"outcome_for": seq, "ts": time.time(),
                     "outcome": dict(outcome)})
        return found

    # ---------------------------------------------------------- surfaces

    def snapshot(self, limit: int = 0) -> List[dict]:
        """Ring contents oldest-first (copies — callers mutate/merge
        freely, e.g. the federated ``/debug/decisions`` host stamp)."""
        with self._lock:
            out = [dict(rec) for rec in self._ring]
        return out[-limit:] if limit > 0 else out

    def status(self) -> dict:
        with self._lock:
            return {
                "records_total": self.records_total,
                "ring": len(self._ring),
                "ring_size": self._ring.maxlen,
                "outcome_horizon_ticks": self.outcome_horizon_ticks,
                "spool_dir": self.spool_dir or None,
                "spool_errors": self.spool_errors,
                "host": self.host or None,
            }

    # ------------------------------------------------------------- spool

    def _spool(self, doc: dict) -> None:
        spool_dir = self.spool_dir
        if not spool_dir:
            return
        try:
            os.makedirs(spool_dir, exist_ok=True)
            path = os.path.join(spool_dir, _SPOOL_NAME)
            try:
                if os.path.getsize(path) >= _SPOOL_MAX_BYTES:
                    os.replace(path, path + ".1")
            except OSError:
                pass                     # no file yet
            with open(path, "a") as f:
                f.write(json.dumps(doc, sort_keys=True) + "\n")
        except (OSError, ValueError, TypeError):
            with self._lock:
                self.spool_errors += 1

    def reset(self) -> None:
        """Test isolation (rides ``telemetry.reset()``)."""
        with self._lock:
            self._ring = collections.deque(maxlen=256)
            self._seq = 0
            self.records_total = 0
            self.spool_dir = ""
            self.spool_errors = 0
            self.outcome_horizon_ticks = 3
            self.host = ""


LEDGER = DecisionLedger()


def record(kind: str, verdict: str, member: str = "",
           detail: Optional[dict] = None) -> int:
    return LEDGER.record(kind, verdict, member=member, detail=detail)


def resolve(seq: int, outcome: dict) -> bool:
    return LEDGER.resolve(seq, outcome)
