"""Deterministic fault injection for the serving chain.

Robustness claims must be falsifiable the same way PR 1 made perf
claims falsifiable: every behavior in the fault-tolerance layer
(deadlines, breaker, shedding, supervision) is exercised by CPU-only
tier-1 tests through THIS seeded chaos layer instead of by prose.

One process-global :class:`FaultInjector` (installed from
``AppConfig.fault_injection``, or directly by tests) is consulted at
fixed hook points:

* ``server.sidecar.SidecarClient.call`` — drop or truncate the request
  frame (the connection dies under the request), or delay it;
* ``server.sidecar`` request handling — self-kill the sidecar process
  after N requests (supervision drills: the crash happens MID-call);
* ``server.batcher`` group renders — raise a transient device error
  (exercises the transient-retry path) or freeze a device lane.

Decisions come from one seeded ``random.Random`` under a lock, so a
fixed seed yields a reproducible fault schedule for a fixed call
sequence.  All rates default to 0 and the module-global injector
defaults to ``None``: the serving hot path pays one ``is None`` check.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from random import Random
from typing import Dict, Optional


class XlaRuntimeError(RuntimeError):
    """Injected transient device error.

    Named so ``utils.transient.is_transient_device_error`` classifies
    it exactly like the real runtime's transport drops — the retry
    path under test is the production one, not a test double."""


@dataclass
class FaultInjectionConfig:
    """``fault-injection`` config block.  ``seed`` None disables the
    whole layer (the production default)."""

    seed: Optional[int] = None
    wire_drop_rate: float = 0.0       # request frame never sent
    wire_truncate_rate: float = 0.0   # partial frame then close
    wire_delay_rate: float = 0.0
    wire_delay_ms: float = 0.0
    device_error_rate: float = 0.0    # transient error in group render
    freeze_rate: float = 0.0          # device lane stalls freeze_ms
    freeze_ms: float = 0.0
    # At most this many freezes are ever injected (0 = unbounded):
    # the watchdog drills need exactly "the first dispatch wedges, the
    # healed requeue runs clean" — a rate alone cannot promise that.
    freeze_max: int = 0
    die_after_requests: int = 0       # sidecar self-kill mid-call

    def validate(self) -> "FaultInjectionConfig":
        for name in ("wire_drop_rate", "wire_truncate_rate",
                     "wire_delay_rate", "device_error_rate",
                     "freeze_rate"):
            v = getattr(self, name)
            if not 0.0 <= v <= 1.0:
                raise ValueError(f"fault-injection.{name} must be in "
                                 f"[0, 1], got {v}")
        if self.wire_delay_ms < 0 or self.freeze_ms < 0:
            raise ValueError("fault-injection delays must be >= 0")
        if self.freeze_max < 0:
            raise ValueError("fault-injection.freeze-max must be >= 0 "
                             "(0 = unbounded)")
        if self.die_after_requests < 0:
            raise ValueError("fault-injection.die-after-requests must "
                             "be >= 0")
        return self


class FaultInjector:
    """Seeded chaos decisions + counters of what was actually injected
    (tests assert the chaos happened; a chaos run that injected nothing
    proves nothing)."""

    def __init__(self, config: FaultInjectionConfig):
        self.config = config.validate()
        self._rng = Random(config.seed)
        self._lock = threading.Lock()
        self._requests_seen = 0
        self.counts: Dict[str, int] = {}

    def _roll(self, rate: float, kind: str) -> bool:
        if rate <= 0.0:
            return False
        with self._lock:
            hit = self._rng.random() < rate
            if hit:
                self.counts[kind] = self.counts.get(kind, 0) + 1
        return hit

    # ------------------------------------------------------ wire faults

    def wire_fault(self) -> Optional[str]:
        """``"drop"`` / ``"truncate"`` / None for the frame about to be
        sent."""
        if self._roll(self.config.wire_drop_rate, "wire_drop"):
            return "drop"
        if self._roll(self.config.wire_truncate_rate, "wire_truncate"):
            return "truncate"
        return None

    def wire_delay_s(self) -> float:
        if self._roll(self.config.wire_delay_rate, "wire_delay"):
            return self.config.wire_delay_ms / 1000.0
        return 0.0

    # ---------------------------------------------------- device faults

    def maybe_device_error(self) -> None:
        """Raise a transient device error at the group-render hook."""
        if self._roll(self.config.device_error_rate, "device_error"):
            raise XlaRuntimeError(
                "injected transient fault: connection reset by peer")

    def freeze_s(self) -> float:
        """Stall duration for the device-lane hook (0 = no stall;
        bounded by ``freeze_max`` total injections when set)."""
        if self.config.freeze_max:
            with self._lock:
                if self.counts.get("freeze", 0) \
                        >= self.config.freeze_max:
                    return 0.0
        if self._roll(self.config.freeze_rate, "freeze"):
            return self.config.freeze_ms / 1000.0
        return 0.0

    # ------------------------------------------------------- supervision

    def sidecar_should_die(self) -> bool:
        """True on the Nth request this process handles (then never
        again — a supervised restart must not die in a loop)."""
        if self.config.die_after_requests <= 0:
            return False
        with self._lock:
            self._requests_seen += 1
            if self._requests_seen == self.config.die_after_requests:
                self.counts["sidecar_kill"] = \
                    self.counts.get("sidecar_kill", 0) + 1
                return True
        return False

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counts)


_INSTALLED: Optional[FaultInjector] = None


def install(config: Optional[FaultInjectionConfig]) -> \
        Optional[FaultInjector]:
    """Install the process-global injector (None / seed-less config
    uninstalls).  Returns the active injector."""
    global _INSTALLED
    if config is None or config.seed is None:
        _INSTALLED = None
    else:
        _INSTALLED = FaultInjector(config)
    return _INSTALLED


def uninstall() -> None:
    global _INSTALLED
    _INSTALLED = None


def active() -> Optional[FaultInjector]:
    return _INSTALLED


# ------------------------------------------------- link-level partitions

class PartitionTable:
    """Asymmetric link-level partitions for the netsplit drill.

    Rules are ``(src_host, dst_host) -> mode`` — DIRECTIONAL, applied
    client-side at the sidecar wire layer of the ``src_host`` process
    (``SidecarClient.call_full`` / ``call_stream`` consult
    :func:`partitioned` before a frame leaves the host).  ``mode``:

    * ``"drop"`` — the link black-holes: the call surfaces as the
      dead-wire ``ConnectionError`` the resilience ladder (retries,
      breaker, mark-down) already handles, after its normal retries;
    * ``"deny"`` — same error surface, counted separately (an
      administratively-refused link vs a silently lossy one).

    Deliberately SEPARATE from the seeded :class:`FaultInjector`:
    partitions are topology state the drill flips on and off (via the
    ``partition`` sidecar wire op), not a random schedule — no seed
    gates them, and they stack with any installed injector.  Rules
    where ``src_host`` is not this process's federation host simply
    never match here (every process carries only its own outbound
    view, exactly like real split routing tables)."""

    MODES = ("drop", "deny")

    def __init__(self):
        self._lock = threading.Lock()
        self._rules: Dict[tuple, str] = {}

    def add(self, src: str, dst: str, mode: str = "drop",
            bidirectional: bool = False) -> None:
        if mode not in self.MODES:
            raise ValueError(f"partition mode must be one of "
                             f"{self.MODES}, got {mode!r}")
        src, dst = str(src), str(dst)
        if not src or not dst or src == dst:
            raise ValueError("partition rule needs distinct non-empty "
                             "src and dst hosts")
        with self._lock:
            self._rules[(src, dst)] = mode
            if bidirectional:
                self._rules[(dst, src)] = mode
        self._publish()

    def remove(self, src: str, dst: str,
               bidirectional: bool = False) -> None:
        with self._lock:
            self._rules.pop((str(src), str(dst)), None)
            if bidirectional:
                self._rules.pop((str(dst), str(src)), None)
        self._publish()

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()
        self._publish()

    def check(self, src: str, dst: str) -> Optional[str]:
        """The blocking mode for src->dst traffic, or None (open
        link).  Unknown/empty hosts are never partitioned — an
        un-federated client (no ``peer_host`` stamp) cannot match."""
        if not src or not dst:
            return None
        with self._lock:
            return self._rules.get((src, dst))

    def snapshot(self) -> list:
        with self._lock:
            return [{"src": s, "dst": d, "mode": m}
                    for (s, d), m in sorted(self._rules.items())]

    def __len__(self) -> int:
        with self._lock:
            return len(self._rules)

    def _publish(self) -> None:
        from . import telemetry
        telemetry.QUORUM.set_partition_rules(len(self))


PARTITIONS = PartitionTable()


def partitioned(src: str, dst: str) -> Optional[str]:
    """Is src->dst traffic blocked right now?  Returns the rule mode
    (counted on ``imageregion_partition_blocked_total``) or None.
    The sidecar client's per-call hook — one dict probe when the
    table is empty."""
    mode = PARTITIONS.check(src, dst)
    if mode is not None:
        from . import telemetry
        telemetry.QUORUM.count_partition_blocked(mode)
    return mode
