"""Transient device-runtime error classification + one-shot retry.

Tunnel/relay transports (remote TPU attachment) surface mid-compile and
mid-transfer connection drops as ``jax.errors.JaxRuntimeError`` with
INTERNAL or UNAVAILABLE status — e.g. ``remote_compile: read body:
response body closed before all bytes were read``.  The program being
launched is fine; re-dispatching over a fresh connection succeeds.  On
co-located hardware these statuses are not produced by healthy
operation, so a single retry is safe everywhere and rescues an entire
render group (or a whole bench section) from one dropped connection.

Deterministic failures — shape errors, tracer leaks,
RESOURCE_EXHAUSTED (HBM OOM) — carry other statuses/types and are NOT
retried.

The check is name-based so device-free processes (frontend proxies) can
import this module without pulling in jax.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")

# Substrings of transient transport statuses (matched case-insensitively
# — strerror text capitalizes "Connection reset by peer"/"Broken pipe").
# Bare status names are too broad on their own: INTERNAL also tags
# compiler bugs, and UNAVAILABLE also tags a persistently dead/detached
# device ("device unavailable"), which a retry would only delay — and
# double-dispatch against.  Both therefore require a transport-flavored
# detail alongside the status.
_TRANSPORT_DETAILS = (
    "read body",
    "response body closed",
    "connection reset",
    "broken pipe",
    "socket closed",
    "transport closed",
    "connection refused",
    "connection closed",
    # gRPC transient texts that carry no socket-level detail.
    "failed to connect",
    "goaway",
    "keepalive",
)


def is_transient_device_error(exc: BaseException) -> bool:
    """True when ``exc`` is a device-runtime error whose message says
    the TRANSPORT (not the program, and not the device itself)
    failed."""
    if type(exc).__name__ not in ("JaxRuntimeError", "XlaRuntimeError"):
        return False
    msg = str(exc).lower()
    return any(marker in msg for marker in _TRANSPORT_DETAILS)


def retry_transient(fn: Callable[[], T], what: str = "device call",
                    backoff_s: float = 2.0) -> T:
    """Run ``fn``; on a transient transport error, retry ONCE after a
    short backoff.  Anything else (including a second transient
    failure) propagates."""
    try:
        return fn()
    except Exception as exc:
        if not is_transient_device_error(exc):
            raise
        logger.warning("%s hit a transient device transport error; "
                       "retrying once: %s", what, exc)
        time.sleep(backoff_s)
        return fn()
