"""Fault-tolerance primitives: transient-error classification, request
deadlines, circuit breaking and op-aware retry policies.

Tunnel/relay transports (remote TPU attachment) surface mid-compile and
mid-transfer connection drops as ``jax.errors.JaxRuntimeError`` with
INTERNAL or UNAVAILABLE status — e.g. ``remote_compile: read body:
response body closed before all bytes were read``.  The program being
launched is fine; re-dispatching over a fresh connection succeeds.  On
co-located hardware these statuses are not produced by healthy
operation, so a single retry is safe everywhere and rescues an entire
render group (or a whole bench section) from one dropped connection.

Deterministic failures — shape errors, tracer leaks,
RESOURCE_EXHAUSTED (HBM OOM) — carry other statuses/types and are NOT
retried.

The check is name-based so device-free processes (frontend proxies) can
import this module without pulling in jax.

On top of that classification this module carries the serving chain's
shared resilience state (the reference leaned on Vert.x supervisor
restarts and bounded event-loop backpressure; these are the TPU build's
equivalents, used by ``server.sidecar`` / ``server.batcher``):

* **Deadlines** — a per-request budget in a ``contextvars`` context.
  ``server.app`` opens the scope, the sidecar wire carries the
  remaining budget, and queued work whose budget is already spent is
  cancelled cooperatively instead of rendered for nobody.
* **CircuitBreaker** — consecutive-failure breaker with a half-open
  probe, so a dead sidecar fails calls fast instead of each request
  paying the full connect-timeout + retry ladder.
* **RetryPolicy** — capped exponential backoff + jitter, applied ONLY
  to idempotent ops; ``plane_put`` (a state-changing upload) is never
  auto-retried.
"""

from __future__ import annotations

import contextvars
import logging
import random
import threading
import time
from contextlib import contextmanager
from typing import Callable, Optional, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")

# Substrings of transient transport statuses (matched case-insensitively
# — strerror text capitalizes "Connection reset by peer"/"Broken pipe").
# Bare status names are too broad on their own: INTERNAL also tags
# compiler bugs, and UNAVAILABLE also tags a persistently dead/detached
# device ("device unavailable"), which a retry would only delay — and
# double-dispatch against.  Both therefore require a transport-flavored
# detail alongside the status.
_TRANSPORT_DETAILS = (
    "read body",
    "response body closed",
    "connection reset",
    "broken pipe",
    "socket closed",
    "transport closed",
    "connection refused",
    "connection closed",
    # gRPC transient texts that carry no socket-level detail.
    "failed to connect",
    "goaway",
    "keepalive",
)


def is_transient_device_error(exc: BaseException) -> bool:
    """True when ``exc`` is a device-runtime error whose message says
    the TRANSPORT (not the program, and not the device itself)
    failed."""
    if type(exc).__name__ not in ("JaxRuntimeError", "XlaRuntimeError"):
        return False
    msg = str(exc).lower()
    return any(marker in msg for marker in _TRANSPORT_DETAILS)


def retry_transient(fn: Callable[[], T], what: str = "device call",
                    backoff_s: float = 2.0) -> T:
    """Run ``fn``; on a transient transport error, retry ONCE after a
    short backoff.  Anything else (including a second transient
    failure) propagates."""
    try:
        return fn()
    except Exception as exc:
        if not is_transient_device_error(exc):
            raise
        logger.warning("%s hit a transient device transport error; "
                       "retrying once: %s", what, exc)
        time.sleep(backoff_s)
        return fn()


# ------------------------------------------------------------- deadlines

class DeadlineExceededError(Exception):
    """The request's time budget is spent (maps to HTTP 504).

    Raised COOPERATIVELY — at pipeline entry, at batcher dispatch pop,
    and on the sidecar wire — never by interrupting running device
    work (a launched XLA program cannot be cancelled anyway)."""


# Absolute time.monotonic() deadline of the current request, or None.
# Set by server.app at request entry; the sidecar wire carries the
# REMAINING budget so the device process re-anchors against its own
# clock (wall clocks never cross the wire).
_DEADLINE: contextvars.ContextVar[Optional[float]] = \
    contextvars.ContextVar("imageregion_deadline", default=None)


@contextmanager
def deadline_scope(budget_ms: Optional[float]):
    """Give the current context ``budget_ms`` of budget from now.
    ``None``/``0`` opens an unbounded scope (explicitly clearing any
    inherited deadline — a detached task must not inherit its spawning
    request's budget)."""
    deadline = (time.monotonic() + budget_ms / 1000.0
                if budget_ms else None)
    token = _DEADLINE.set(deadline)
    try:
        yield deadline
    finally:
        _DEADLINE.reset(token)


def set_task_deadline(budget_ms: Optional[float]) -> None:
    """Give the CURRENT task's context ``budget_ms`` of budget from
    now.  Wire semantics, unlike ``deadline_scope``'s config
    semantics: ``None`` (no header) is unbounded, but ``0`` is a
    budget that is ALREADY SPENT — a request arriving with nothing
    left must 504, not run forever.  No scope token to restore: this
    is for per-request asyncio tasks, whose context dies with them —
    a generator-scope here would only leak "created in a different
    Context" noise when the task is cancelled mid-request."""
    _DEADLINE.set(None if budget_ms is None
                  else time.monotonic() + budget_ms / 1000.0)


def clear_deadline() -> None:
    """Detach the current context from any inherited deadline (for
    long-lived tasks spawned from inside a request that must not run
    on its budget)."""
    _DEADLINE.set(None)


def deadline() -> Optional[float]:
    """The context's absolute monotonic deadline, or None."""
    return _DEADLINE.get()


def remaining_ms() -> Optional[float]:
    """Milliseconds of budget left (may be <= 0), or None (unbounded)."""
    d = _DEADLINE.get()
    if d is None:
        return None
    return (d - time.monotonic()) * 1000.0


def check_deadline(what: str = "request") -> None:
    """Cooperative cancellation point: raise when the budget is spent."""
    d = _DEADLINE.get()
    if d is not None and time.monotonic() >= d:
        raise DeadlineExceededError(f"{what}: deadline exceeded")


# -------------------------------------------------------- circuit breaker

class CircuitBreaker:
    """Consecutive-failure circuit breaker with a half-open probe.

    closed -> (``failure_threshold`` consecutive failures) -> open ->
    (``reset_after_s`` elapses) -> half-open: ONE trial call is
    admitted; its success closes the breaker, its failure re-opens it
    for another ``reset_after_s``.

    Thread-safe; the clock is injectable so tests drive state
    transitions deterministically."""

    CLOSED, HALF_OPEN, OPEN = 0, 1, 2
    _NAMES = {0: "closed", 1: "half-open", 2: "open"}

    def __init__(self, failure_threshold: int = 5,
                 reset_after_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_after_s = reset_after_s
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probing = False
        self._probe_started_at = 0.0
        self.opens = 0          # /metrics counter: closed/half -> open

    @property
    def state(self) -> int:
        with self._lock:
            return self._effective_state()

    @property
    def state_name(self) -> str:
        return self._NAMES[self.state]

    def _effective_state(self) -> int:
        # Lock held.  OPEN decays to HALF_OPEN by clock, not by a
        # background task — breakers must work in processes with no
        # event loop running.
        if (self._state == self.OPEN
                and self._clock() - self._opened_at >= self.reset_after_s):
            self._state = self.HALF_OPEN
            self._probing = False
        return self._state

    def allow(self) -> bool:
        """Whether a call may proceed now.  In half-open, one caller
        at a time holds the trial slot — but the slot EXPIRES after
        ``reset_after_s``: a probe whose caller never reported an
        outcome (cancelled mid-call, deadline fired between allow()
        and the send) must not wedge the breaker into shedding
        forever."""
        with self._lock:
            state = self._effective_state()
            if state == self.CLOSED:
                return True
            if state == self.HALF_OPEN and (
                    not self._probing
                    or self._clock() - self._probe_started_at
                    >= self.reset_after_s):
                self._probing = True
                self._probe_started_at = self._clock()
                return True
            return False

    def retry_after_s(self) -> float:
        """How long until the breaker will admit a trial call — the
        shed response's Retry-After."""
        with self._lock:
            if self._effective_state() != self.OPEN:
                return 0.0
            return max(0.0, self.reset_after_s
                       - (self._clock() - self._opened_at))

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._state = self.CLOSED
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            state = self._effective_state()
            self._failures += 1
            if state == self.HALF_OPEN or (
                    state == self.CLOSED
                    and self._failures >= self.failure_threshold):
                if self._state != self.OPEN:
                    self.opens += 1
                self._state = self.OPEN
                self._opened_at = self._clock()
                self._probing = False


# ------------------------------------------------------------ retry policy

# Sidecar ops safe to re-issue against a peer that may or may not have
# executed the original: renders and probes are pure reads, ping and
# metrics are trivially repeatable.  plane_put is NOT here — it mutates
# device-cache state and its digest verification makes a duplicate
# upload wasted wire bytes at best, so the caller decides.
IDEMPOTENT_OPS = frozenset({"image", "mask", "ping", "metrics",
                            "plane_probe",
                            # Drain surfaces: the manifest is a pure
                            # read; prestage re-stages through the
                            # digest-deduped path, so a duplicate is a
                            # no-op probe hit, never double state.
                            "shard_manifest", "prestage",
                            # Fleet-global byte tier: presence probe
                            # and byte read are pure reads.  byte_put
                            # (the peer write-back) is NOT here — like
                            # plane_put it mutates cache state, and a
                            # blind re-send is wasted wire bytes at
                            # best; the caller decides.
                            "byte_probe", "byte_fetch",
                            # Cross-host federation: the manifest
                            # exchange and the gossip swap are pure
                            # state reads on both ends (merge is
                            # newest-ts idempotent).  shard_transfer
                            # is NOT here — it ships cache state, the
                            # plane_put posture.
                            "manifest_hello", "member_gossip",
                            # Two-phase epoch rolls are idempotent BY
                            # CONTRACT (a re-propose re-acks the same
                            # pending manifest; a re-commit of the
                            # active epoch answers already-active), so
                            # a coordinator may retry them across a
                            # flaky link without double-rolling.  The
                            # partition op sets/clears absolute rules
                            # — a duplicate is a no-op, and the HEAL
                            # call must survive a lossy drill link.
                            "epoch_propose", "epoch_commit",
                            "partition"})


class RetryPolicy:
    """Capped exponential backoff + jitter for idempotent ops.

    ``rng`` is injectable so tests (and the seeded chaos harness) get
    deterministic backoff sequences."""

    def __init__(self, max_attempts: int = 3,
                 base_backoff_s: float = 0.025,
                 max_backoff_s: float = 1.0,
                 jitter: float = 0.5,
                 rng: Optional[random.Random] = None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.base_backoff_s = base_backoff_s
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self._rng = rng or random.Random()

    def attempts_for(self, op: str) -> int:
        """How many total attempts ``op`` gets: the full ladder for
        idempotent ops, exactly one for anything state-changing."""
        return self.max_attempts if op in IDEMPOTENT_OPS else 1

    def backoff_s(self, attempt: int) -> float:
        """Sleep before attempt ``attempt + 1`` (attempt is 0-based):
        ``base * 2^attempt`` capped at ``max``, plus up to ``jitter``
        of itself so a burst of failed requests does not retry in
        lockstep."""
        backoff = min(self.base_backoff_s * (2 ** attempt),
                      self.max_backoff_s)
        return backoff * (1.0 + self.jitter * self._rng.random())
