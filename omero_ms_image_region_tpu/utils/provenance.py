"""Response provenance: where each byte came from, per request.

PRs 8-11 turned one render into a distributed outcome — a request may
be answered by a 304, the byte cache's memory or disk tier, a fleet
peer's byte tier, a warm HBM plane, a cold render (possibly STOLEN by
another member, or failed over after a death, or re-homed by a rolling
drain), or the degraded CPU path.  The access log and /metrics could
not say which.  This module is the one vocabulary for that answer:

* a **provenance record** is a small dict assembled per finished
  request from marks the serving layers left on the request ctx
  (``mark``) — serving member, byte-source tier, steal/failover/drain
  flags, QoS class, the engaged pressure-ladder prefix, and the
  session tokens the fairness gate charged;
* the record lands on the JSON access line (``prov``), feeds the
  ``imageregion_provenance_*`` counters (closed label sets — TIERS and
  FLAGS below are the entire vocabulary), and can be echoed as the
  opt-in ``X-Image-Region-Provenance`` debug header
  (``telemetry.provenance-header``, never on errors).

Marks cross the sidecar wire as the optional ``prov`` response key
(``server.sidecar``), so a fleet frontend's record names the REMOTE
member that actually did the work.  Device-free on import.
"""

from __future__ import annotations

from typing import Dict, Optional

# The byte-source tiers, cheapest first.  CLOSED: these seven strings
# are the entire ``tier`` label vocabulary on /metrics — a new tier is
# a deliberate schema change here, never an ad-hoc string at a call
# site (the exposition lint + scripts/metrics_lint.py budget hold it).
TIERS = ("304", "byte_cache", "peer", "disk", "hbm_warm",
         "render_cold", "degraded")

# Routing/serving flags a request may carry (each 0/1): CLOSED, the
# ``flag`` label vocabulary.
FLAGS = ("stolen", "failed_over", "drain_rehomed", "coalesced",
         "quality_capped")

_ATTR = "_provenance"


def mark(ctx, **fields) -> None:
    """Merge provenance fields onto the request ctx (lazily created
    dict — requests that never hit a marking layer pay one getattr).
    Later marks win for scalar fields; use :func:`merge_wire` for the
    sidecar import, which must NOT clobber frontend-side marks."""
    prov = getattr(ctx, _ATTR, None)
    if prov is None:
        prov = {}
        setattr(ctx, _ATTR, prov)
    prov.update(fields)


def marks(ctx) -> Dict:
    """The ctx's accumulated marks (read-only view; {} when none)."""
    return getattr(ctx, _ATTR, None) or {}


def merge_wire(ctx, wire_prov) -> None:
    """Graft a sidecar-exported ``prov`` dict onto the frontend ctx.
    Frontend-side marks take precedence (the router knows WHICH member
    it dispatched to; the sidecar only knows what it did locally)."""
    if not isinstance(wire_prov, dict):
        return
    prov = getattr(ctx, _ATTR, None)
    if prov is None:
        prov = {}
        setattr(ctx, _ATTR, prov)
    for key, value in wire_prov.items():
        prov.setdefault(str(key), value)


def assemble(ctx, status: int,
             trace_id: Optional[str] = None) -> Dict:
    """The finished request's provenance record.

    Pure function of the ctx marks + status: the tier defaults to
    ``render_cold`` (a request no cheaper layer claimed paid the full
    pipeline), 304s override everything (no byte moved at all), and
    the degraded CPU path overrides the tier a failed attempt may have
    marked first.  The live pressure-ladder prefix and QoS class are
    read here, once, at finish time."""
    m = marks(ctx)
    if status == 304:
        tier = "304"
    else:
        tier = m.get("tier") or "render_cold"
        if tier not in TIERS:          # a drifted call site: stay
            tier = "render_cold"       # inside the closed vocabulary
    record: Dict = {"tier": tier, "member": m.get("member") or "-"}
    for flag in FLAGS:
        if m.get(flag):
            record[flag] = 1
    if m.get("quality_capped") is None \
            and getattr(ctx, "_pressure_quality_capped", False):
        record["quality_capped"] = 1
    # QoS class: the ONE classification the ladder/fleet pin share.
    # The narrow except covers exactly the mask-ctx case (no
    # tile/region/projection attributes); the governor read runs
    # OUTSIDE it so a mask request still reports the engaged ladder.
    from ..server.pressure import active, is_bulk
    try:
        bulk = is_bulk(ctx)
    except AttributeError:             # mask ctxs have no tile/proj
        bulk = False
    record["qos"] = "bulk" if bulk else "interactive"
    governor = active()
    if governor is not None and governor.engaged_steps():
        record["ladder"] = ",".join(governor.engaged_steps())
    tokens = m.get("tokens")
    if tokens:
        record["tokens"] = round(float(tokens), 3)
    if trace_id:
        record["trace"] = trace_id
    return record


def header_value(record: Dict) -> str:
    """Compact ``k=v; k=v`` form for the debug header (header-safe:
    values are this module's own closed vocabulary, member names from
    config, and numbers — never client input)."""
    parts = []
    for key in ("tier", "member", "qos", "ladder", "tokens", "trace"):
        value = record.get(key)
        if value not in (None, "", "-"):
            parts.append(f"{key}={value}")
    flags = [f for f in FLAGS if record.get(f)]
    if flags:
        parts.append("flags=" + ",".join(flags))
    return "; ".join(parts)
