"""SipHash-2-4 with Guava-compatible output formatting.

The reference derives its Redis cache keys with Guava's
``Hashing.sipHash24()`` over a canonical parameter string
(``ImageRegionCtx.java:165-177``).  To stay cache-compatible with a Java
deployment (same Redis, same keys), this module reproduces:

  * the SipHash-2-4 algorithm (Aumasson & Bernstein) with Guava's default
    seed k0=0x0706050403020100, k1=0x0f0e0d0c0b0a0908,
  * Guava's ``HashCode.toString()`` formatting: the 64-bit result printed
    as its 8 bytes in little-endian order, lower-case hex.

A C implementation lives in native/ for the hot path; this pure-Python
version is the always-available fallback and the golden reference for it.
"""

from __future__ import annotations

MASK = 0xFFFFFFFFFFFFFFFF

GUAVA_K0 = 0x0706050403020100
GUAVA_K1 = 0x0F0E0D0C0B0A0908


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & MASK


def siphash24(data: bytes, k0: int = GUAVA_K0, k1: int = GUAVA_K1) -> int:
    """SipHash-2-4 of ``data``; returns the 64-bit hash as an int."""
    v0 = 0x736F6D6570736575 ^ k0
    v1 = 0x646F72616E646F6D ^ k1
    v2 = 0x6C7967656E657261 ^ k0
    v3 = 0x7465646279746573 ^ k1

    def sipround():
        nonlocal v0, v1, v2, v3
        v0 = (v0 + v1) & MASK
        v1 = _rotl(v1, 13)
        v1 ^= v0
        v0 = _rotl(v0, 32)
        v2 = (v2 + v3) & MASK
        v3 = _rotl(v3, 16)
        v3 ^= v2
        v0 = (v0 + v3) & MASK
        v3 = _rotl(v3, 21)
        v3 ^= v0
        v2 = (v2 + v1) & MASK
        v1 = _rotl(v1, 17)
        v1 ^= v2
        v2 = _rotl(v2, 32)

    n = len(data)
    end = n - (n % 8)
    for off in range(0, end, 8):
        m = int.from_bytes(data[off:off + 8], "little")
        v3 ^= m
        sipround()
        sipround()
        v0 ^= m

    b = (n & 0xFF) << 56
    tail = data[end:]
    for i, byte in enumerate(tail):
        b |= byte << (8 * i)
    v3 ^= b
    sipround()
    sipround()
    v0 ^= b

    v2 ^= 0xFF
    sipround()
    sipround()
    sipround()
    sipround()
    return (v0 ^ v1 ^ v2 ^ v3) & MASK


def guava_siphash24_hex(text: str) -> str:
    """Hash a string as Guava's ``sipHash24().hashString(s, UTF_8).toString()``
    would: UTF-8 encode, SipHash-2-4, print result bytes little-endian hex."""
    h = siphash24(text.encode("utf-8"))
    return h.to_bytes(8, "little").hex()
