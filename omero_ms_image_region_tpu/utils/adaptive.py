"""Adaptive JPEG wire-engine selection.

``renderer.jpeg-engine: auto`` used to probe the device->host link once
at startup (``utils.linkprobe``) and freeze the choice — but tunnel
links swing 5-700 MB/s over minutes, and the wrong engine costs ~40%
service throughput (the sparse wire stalls on a congested link; the
huffman engine wastes a fast one).  This controller keeps the choice
live:

- every sparse wire fetch big enough to be bandwidth-dominated feeds an
  EWMA of the observed link rate (``observe_fetch`` — wired into the
  fetchers by ``ops.jpegenc.set_fetch_observer``);
- the engine flips when the EWMA crosses the sparse/huffman crossover
  with hysteresis (a band, so link noise cannot thrash engines — each
  flip costs a one-time compile of the other engine's program);
- while in huffman (whose small fetches are latency-dominated and say
  nothing useful about bandwidth) — and after any idle gap — the link
  is re-probed with a real transfer, so recovery back to sparse is
  observed rather than assumed.

Pod-safe on multi-host meshes by construction: the engines build
different SPMD programs, so per-HOST flips would diverge the pod —
instead ONLY the leader consults the controller, at group boundaries,
and the chosen engine rides the existing per-group pod announcement
(``parallel/serve.py``), so every process launches the identical
sharded program for each group.

Reference analogue: the compression level/codec applied per render in
``ImageRegionRequestHandler.java:559,580-582`` — here the *wire format*
adapts per group instead.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from .linkprobe import AUTO_SPARSE_MIN_MB_S, measure_fetch_mb_s

logger = logging.getLogger(__name__)

# Fetches below this are latency-dominated and carry no bandwidth
# signal (the tunnel RTT floor is ~100 ms; 256 KB at the 12 MB/s
# crossover is ~21 ms — anything smaller mostly measures the floor).
MIN_OBSERVATION_BYTES = 256 * 1024


class AdaptiveEngine:
    """EWMA link-rate tracker choosing "sparse" or "huffman" live."""

    def __init__(self,
                 initial_engine: Optional[str] = None,
                 initial_rate_mb_s: Optional[float] = None,
                 crossover_mb_s: float = AUTO_SPARSE_MIN_MB_S,
                 hysteresis: float = 0.25,
                 alpha: float = 0.3,
                 reprobe_interval_s: float = 20.0,
                 idle_reprobe_s: float = 30.0,
                 probe: Callable[[], float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.crossover = crossover_mb_s
        self.hysteresis = hysteresis
        self.alpha = alpha
        self.reprobe_interval_s = reprobe_interval_s
        self.idle_reprobe_s = idle_reprobe_s
        # Re-probes run mid-serving: keep them lighter than the startup
        # probe (1 MB x 2 vs 4 MB x 3).
        self._probe = probe or (
            lambda: measure_fetch_mb_s(nbytes=1 << 20, repeats=2))
        self._clock = clock
        self._lock = threading.Lock()
        self.rate_mb_s = initial_rate_mb_s
        if initial_engine is None:
            initial_engine = self._pick(initial_rate_mb_s, "sparse")
        self.engine = initial_engine
        self.switches = 0            # metrics / tests
        self._suspect = 0
        self._probe_due = False
        now = clock()
        self._last_observation = now
        self._last_probe = now

    # Consecutive low conflated (compute-synced) readings before a real
    # probe is forced; see observe_fetch.
    SUSPECT_STREAK = 4

    # ------------------------------------------------------------ policy

    def _pick(self, rate: Optional[float], current: str) -> str:
        """Hysteresis band around the crossover: flip only on a clear
        signal, hold inside the band."""
        if rate is None:
            return current
        hi = self.crossover * (1.0 + self.hysteresis)
        lo = self.crossover * (1.0 - self.hysteresis)
        if rate >= hi:
            return "sparse"
        if rate <= lo:
            return "huffman"
        return current

    def _update(self, rate_sample: float, replace: bool = False) -> None:
        """Caller holds the lock.  ``replace`` skips the EWMA blend —
        used for explicit probes, which are direct link measurements
        that must not be damped by a stale estimate (an idle gap means
        the EWMA describes a link that may no longer exist)."""
        if replace or self.rate_mb_s is None:
            self.rate_mb_s = rate_sample
        else:
            self.rate_mb_s = (self.alpha * rate_sample
                              + (1.0 - self.alpha) * self.rate_mb_s)
        new = self._pick(self.rate_mb_s, self.engine)
        if new != self.engine:
            self.switches += 1
            logger.info(
                "adaptive wire engine: %s -> %s (link EWMA %.1f MB/s, "
                "crossover %.1f MB/s)", self.engine, new,
                self.rate_mb_s, self.crossover)
            self.engine = new

    # ------------------------------------------------------------ inputs

    def observe_fetch(self, nbytes: int, seconds: float,
                      conflated: bool = False) -> None:
        """Feed one device->host wire fetch (called from the fetchers).

        Small fetches are ignored (latency-dominated); the timestamp
        still counts as activity so idle detection stays honest.

        ``conflated`` samples timed device execution along with the
        transfer, so their rate is only a LOWER BOUND on the link: a
        high reading is real evidence (the link carried at least that),
        but a low one cannot distinguish slow-link from slow-compute.
        Low conflated readings therefore never feed the EWMA directly —
        they accumulate suspicion that triggers a real probe on the
        next :meth:`current` call instead.
        """
        now = self._clock()
        with self._lock:
            self._last_observation = now
            if nbytes < MIN_OBSERVATION_BYTES or seconds <= 0:
                return
            rate = nbytes / 1e6 / seconds
            if conflated:
                if rate >= self.crossover * (1.0 + self.hysteresis):
                    # Lower bound already above the sparse band: safe
                    # to count (the true rate is even higher).
                    self._suspect = 0
                    self._update(rate)
                elif self.engine == "sparse":
                    self._suspect += 1
                    if self._suspect >= self.SUSPECT_STREAK:
                        # Persistently low lower-bounds: force a real
                        # probe at the next engine query.
                        self._last_probe = (
                            now - self.reprobe_interval_s)
                        self._probe_due = True
                return
            self._suspect = 0
            self._update(rate)

    def current(self) -> str:
        """The engine to use for the next group.

        Runs on the render worker thread, so a due re-probe (huffman
        steady state, or an idle gap) may block briefly on a real
        transfer — that is the price of *observing* link recovery
        instead of assuming it.
        """
        now = self._clock()
        with self._lock:
            idle = (now - self._last_observation) >= self.idle_reprobe_s
            stale = (self.engine == "huffman"
                     and (now - self._last_probe)
                     >= self.reprobe_interval_s)
            suspect = self._probe_due
            if not (idle or stale or suspect):
                return self.engine
            self._probe_due = False
            self._suspect = 0
            self._last_probe = now
            self._last_observation = now
        try:
            rate = self._probe()
        except Exception:
            logger.warning("adaptive engine re-probe failed",
                           exc_info=True)
            return self.engine
        with self._lock:
            self._update(rate, replace=True)
            return self.engine
