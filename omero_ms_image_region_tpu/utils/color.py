"""HTML color string parsing.

Reimplements the color splitter consumed at
``ImageRegionRequestHandler.java:865-890`` (and by the mask renderer at
``ShapeMaskRequestHandler.java:103-105``):

    abc      -> (0xAA, 0xBB, 0xCC, 0xFF)
    abcd     -> (0xAA, 0xBB, 0xCC, 0xDD)
    abbccd   -> (0xAB, 0xBC, 0xCD, 0xFF)
    abbccdde -> (0xAB, 0xBC, 0xCD, 0xDE)

Returns None for anything unparseable (the reference logs and returns null).

Deliberate deviation: the reference's 3/4-char expansion is broken in Java
(``color += ch + ch`` promotes chars to ints, building digit strings like
"194" — so 3/4-char colors always return null despite the documented
table).  This module implements the documented/intended behavior, which is
also what OMERO.web's own Python splitHTMLColor does.
"""

from __future__ import annotations

from typing import Optional, Tuple


def split_html_color(color: str) -> Optional[Tuple[int, int, int, int]]:
    try:
        if len(color) in (3, 4):
            color = "".join(ch + ch for ch in color)
        if len(color) == 6:
            color += "FF"
        if len(color) == 8:
            return (
                int(color[0:2], 16),
                int(color[2:4], 16),
                int(color[4:6], 16),
                int(color[6:8], 16),
            )
    except (ValueError, TypeError):
        pass
    return None
