"""Device->host link probe: pick the JPEG wire engine for this link.

The two batched wire engines trade device time against wire bytes
(``ops/jpegenc.py``): "sparse" ships ~0.29 MB per 1024d tile and spends
almost no device time packing; "huffman" packs the full fixed-table
bitstream on device (~0.08 MB/tile, ~3.6x fewer bytes) but its deposit
scatters bound it to ~35-40 tiles/s of device throughput.  Sparse
therefore wins exactly when the link can carry its extra bytes faster
than huffman renders: rate > huffman_ceiling * sparse_bytes/tile
~= 38 * 0.29 ~= 11 MB/s.  ``renderer.jpeg-engine: auto`` measures the
link once at startup and picks accordingly — co-located TPUs (GB/s
class) get sparse, congested tunnels get huffman.
"""

from __future__ import annotations

import logging
import time

logger = logging.getLogger(__name__)

# Crossover (MB/s) above which the sparse wire out-runs the huffman
# engine's device-bound ceiling; see module docstring for the arithmetic.
AUTO_SPARSE_MIN_MB_S = 12.0


def measure_fetch_mb_s(nbytes: int = 4 << 20, repeats: int = 3) -> float:
    """Best-of-N device->host fetch bandwidth in MB/s.

    Each repeat fetches a DISTINCT random buffer so relay-side content
    caching (observed on tunnel transports for repeated identical
    payloads) cannot inflate the estimate.
    """
    import jax
    import numpy as np

    rng = np.random.default_rng(0)
    best = float("inf")
    for _ in range(repeats):
        x = jax.device_put(rng.integers(0, 255, nbytes, dtype=np.uint8))
        jax.block_until_ready(x)
        t0 = time.perf_counter()
        np.asarray(x)
        best = min(best, time.perf_counter() - t0)
    return nbytes / 1e6 / best


def resolve_auto_engine() -> str:
    """Measure the link and return "sparse" or "huffman".

    In a multi-host pod every process MUST resolve to the same engine —
    the engines build different shard_map programs over the same global
    mesh, and divergence hangs the pod (SPMD).  Hosts can sit on opposite
    sides of the crossover (one fast NIC, one congested), so the local
    rate is all-gathered and the pod-wide MINIMUM decides: the slowest
    link is the one the sparse wire would actually stall on.
    """
    try:
        rate = measure_fetch_mb_s()
    except Exception:
        # Do NOT early-return here: in a pod every process must still
        # join the allgather below or the others hang.  inf = "link
        # unknown; don't drag the pod minimum down"; if every probe
        # fails the inf survives and the >= crossover test lands on
        # sparse, preserving the single-host failure default.
        logger.warning("link probe failed; treating link rate as "
                       "unknown", exc_info=True)
        rate = float("inf")
    import jax
    if jax.process_count() > 1:
        import numpy as np
        from jax.experimental import multihost_utils
        rates = np.asarray(
            multihost_utils.process_allgather(np.float32(rate)))
        pod_rate = float(rates.min())
        logger.info("link probe (pod): local %.1f MB/s, pod min %.1f MB/s "
                    "across %d hosts", rate, pod_rate, rates.size)
        rate = pod_rate
    engine = "sparse" if rate >= AUTO_SPARSE_MIN_MB_S else "huffman"
    logger.info("link probe: %.1f MB/s device->host -> jpeg engine %r "
                "(crossover %.0f MB/s)", rate, engine, AUTO_SPARSE_MIN_MB_S)
    return engine
