"""Host utilities: hashing, colors, config, telemetry (request traces,
bucketed histograms, link health, readiness state)."""

from .siphash import siphash24, guava_siphash24_hex
from .color import split_html_color

__all__ = ["siphash24", "guava_siphash24_hex", "split_html_color"]
