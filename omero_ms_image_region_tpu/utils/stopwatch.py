"""Stage timing spans (≙ perf4j ``Slf4JStopWatch``).

The reference wraps every pipeline stage in a named stopwatch whose
start/elapsed pairs double as latency metrics in the logs (SURVEY.md §5:
``ImageRegionVerticle.java:148``, ``ImageRegionRequestHandler.java:189,303,
343,502,522``).  The span names are kept verbatim so dashboards built on the
Java service's logs keep working against this one.

Spans log at debug level and feed an in-process aggregator that the OPTIONS
endpoint / tests can read back (count, total, p50-ish via ring buffer).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict

log = logging.getLogger("omero_ms_image_region_tpu.perf")

_RING = 256


class SpanStats:
    __slots__ = ("count", "total_ms", "recent")

    def __init__(self):
        self.count = 0
        self.total_ms = 0.0
        self.recent = deque(maxlen=_RING)

    def add(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        self.recent.append(ms)

    def p50(self) -> float:
        if not self.recent:
            return 0.0
        return sorted(self.recent)[len(self.recent) // 2]

    def as_dict(self) -> dict:
        return {
            "count": self.count,
            "total_ms": round(self.total_ms, 3),
            "mean_ms": round(self.total_ms / self.count, 3)
            if self.count else 0.0,
            "p50_ms": round(self.p50(), 3),
        }


class StopWatchRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._spans: Dict[str, SpanStats] = {}

    def record(self, name: str, ms: float) -> None:
        with self._lock:
            stats = self._spans.get(name)
            if stats is None:
                stats = self._spans[name] = SpanStats()
            stats.add(ms)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {name: s.as_dict() for name, s in self._spans.items()}

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


REGISTRY = StopWatchRegistry()


def span_lines(extra_labels: str = "",
               registry: StopWatchRegistry = REGISTRY) -> list:
    """Prometheus exposition lines for every span — the one formatter
    shared by the app's /metrics and the sidecar's metrics op.

    ``extra_labels`` is appended inside the label braces (e.g.
    ``,process="sidecar"``)."""
    lines = []
    for name, s in sorted(registry.snapshot().items()):
        label = f'{{span="{name}"{extra_labels}}}'
        lines += [
            f"imageregion_span_count{label} {s['count']}",
            f"imageregion_span_mean_ms{label} {s['mean_ms']}",
            f"imageregion_span_p50_ms{label} {s['p50_ms']}",
        ]
    return lines


@contextmanager
def stopwatch(name: str, registry: StopWatchRegistry = REGISTRY):
    """Time a stage under a reference span name, e.g.
    ``Renderer.renderAsPackedInt`` or ``ProjectionService.projectStack``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        ms = (time.perf_counter() - t0) * 1000.0
        registry.record(name, ms)
        log.debug("time[%s] = %.3f ms", name, ms)
