"""Stage timing spans (≙ perf4j ``Slf4JStopWatch``).

The reference wraps every pipeline stage in a named stopwatch whose
start/elapsed pairs double as latency metrics in the logs (SURVEY.md §5:
``ImageRegionVerticle.java:148``, ``ImageRegionRequestHandler.java:189,303,
343,502,522``).  The span names are kept verbatim so dashboards built on the
Java service's logs keep working against this one.

Spans log at debug level and feed an in-process aggregator exposed on
``/metrics``.  Each span keeps a fixed log-scale bucketed histogram
(``utils.telemetry.Histogram``) — proper Prometheus
``_bucket``/``_sum``/``_count`` series, replacing the old 256-sample
ring whose p50 hid tail regressions.  Every recorded duration is also
offered to the active request trace(s) (``telemetry.observe_span``), so
stage timings double as waterfall child spans.
"""

from __future__ import annotations

import logging
import threading
import time
from contextlib import contextmanager
from typing import Dict

from .telemetry import Histogram, observe_span

log = logging.getLogger("omero_ms_image_region_tpu.perf")


class SpanStats:
    __slots__ = ("count", "total_ms", "max_ms", "hist")

    def __init__(self):
        self.count = 0
        self.total_ms = 0.0
        self.max_ms = 0.0
        self.hist = Histogram()

    def add(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        if ms > self.max_ms:
            self.max_ms = ms
        self.hist.add(ms)

    def as_dict(self) -> dict:
        return {
            "count": self.count,
            "total_ms": round(self.total_ms, 3),
            "mean_ms": round(self.total_ms / self.count, 3)
            if self.count else 0.0,
            # Bucket-resolution estimate (upper bucket bound), kept for
            # the profiling scripts that read the old ring p50.
            "p50_ms": round(self.hist.quantile(0.5), 3),
            # Tail breakdown: BENCH_r05's batcher.queueWait showed mean
            # 2276 ms against p50 2.2 ms — a heavy tail a mean conflates
            # and a p50 cannot see.  p95/p99 are bucket-resolution
            # estimates like p50; max is exact.
            "p95_ms": round(self.hist.quantile(0.95), 3),
            "p99_ms": round(self.hist.quantile(0.99), 3),
            "max_ms": round(self.max_ms, 3),
        }


class StopWatchRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._spans: Dict[str, SpanStats] = {}

    def record(self, name: str, ms: float) -> None:
        with self._lock:
            stats = self._spans.get(name)
            if stats is None:
                stats = self._spans[name] = SpanStats()
            stats.add(ms)
        # Outside the lock: trace recording takes the trace's own lock.
        observe_span(name, ms)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            return {name: s.as_dict() for name, s in self._spans.items()}

    def histograms(self) -> Dict[str, Histogram]:
        """Shallow snapshot of the live histograms (read-only use)."""
        with self._lock:
            return dict((name, s.hist)
                        for name, s in self._spans.items())

    def reset(self) -> None:
        with self._lock:
            self._spans.clear()


REGISTRY = StopWatchRegistry()


def span_lines(extra_labels: str = "",
               registry: StopWatchRegistry = REGISTRY) -> list:
    """Prometheus exposition lines for every span — the one formatter
    shared by the app's /metrics and the sidecar's metrics op.

    Per span: the legacy count/mean series plus the full
    ``imageregion_span_ms`` histogram (``_bucket``/``_sum``/``_count``).
    ``extra_labels`` is appended inside the label braces (e.g.
    ``,process="sidecar"``)."""
    extra = extra_labels.lstrip(",")
    lines = []
    with registry._lock:
        items = sorted((name, s.count, s.total_ms, s.hist)
                       for name, s in registry._spans.items())
        for name, count, total_ms, hist in items:
            body = f'span="{name}"' + (f",{extra}" if extra else "")
            mean = round(total_ms / count, 3) if count else 0.0
            lines += [
                f"imageregion_span_count{{{body}}} {count}",
                f"imageregion_span_mean_ms{{{body}}} {mean}",
            ]
            lines += hist.series("imageregion_span_ms", body)
    return lines


@contextmanager
def stopwatch(name: str, registry: StopWatchRegistry = REGISTRY):
    """Time a stage under a reference span name, e.g.
    ``Renderer.renderAsPackedInt`` or ``ProjectionService.projectStack``."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        ms = (time.perf_counter() - t0) * 1000.0
        registry.record(name, ms)
        log.debug("time[%s] = %.3f ms", name, ms)
