"""The flagship benchmark workload, defined once.

BASELINE.md config 3 — 4-channel uint16 WSI tiles rendered to RGB — is both
the driver's compile-check entry (``__graft_entry__.py``) and the headline
bench workload (``bench.py``).  Both import this module so the two can never
drift apart.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .models.pixels import Pixels
from .models.rendering import (RenderingDef, RenderingModel,
                               default_rendering_def)
from .ops.render import pack_settings

FLAGSHIP_COLORS = ((255, 0, 0), (0, 255, 0), (0, 0, 255), (255, 255, 0))
FLAGSHIP_WINDOW = (100.0, 40000.0)


def flagship_rdef(n_channels: int = 4,
                  plane: int = 8192) -> RenderingDef:
    """RGB rendering settings for the n-channel 16-bit WSI workload."""
    pixels = Pixels(
        image_id=1, size_x=plane, size_y=plane, size_z=1,
        size_c=n_channels, size_t=1, pixels_type="uint16",
    )
    rdef = default_rendering_def(pixels)
    rdef.model = RenderingModel.RGB
    for i, cb in enumerate(rdef.channel_bindings):
        cb.active = True
        cb.red, cb.green, cb.blue = FLAGSHIP_COLORS[i % len(FLAGSHIP_COLORS)]
        cb.input_start, cb.input_end = FLAGSHIP_WINDOW
    return rdef


def flagship_settings(n_channels: int = 4) -> Tuple[RenderingDef, dict]:
    rdef = flagship_rdef(n_channels)
    return rdef, pack_settings(rdef)


def synthetic_wsi_tiles(rng: np.random.Generator, B: int, C: int,
                        H: int, W: int, blobs: int = 12) -> np.ndarray:
    """Synthetic microscopy-like uint16 tiles: cell blobs + sensor noise.

    Gaussian blobs (separable outer products, so generation stays cheap at
    1024^2) over a dim background with additive read noise — the content
    class the 4-ch WSI benchmark config describes, rather than uniform
    random noise, which no microscope produces and which no codec or cache
    behaves representatively on.
    """
    cy = rng.uniform(0, H, size=(B, C, blobs, 1))
    cx = rng.uniform(0, W, size=(B, C, blobs, 1))
    s = rng.uniform(H / 40, H / 8, size=(B, C, blobs, 1))
    amp = rng.uniform(5_000, 35_000, size=(B, C, blobs))
    ys = np.exp(-((np.arange(H)[None, None, None, :] - cy) ** 2)
                / (2 * s * s)).astype(np.float32)
    xs = np.exp(-((np.arange(W)[None, None, None, :] - cx) ** 2)
                / (2 * s * s)).astype(np.float32)
    img = np.einsum("bcky,bckx,bck->bcyx", ys, xs,
                    amp.astype(np.float32), optimize=True)
    img += 200.0 + rng.normal(0, 300.0, size=img.shape)
    return np.clip(img, 0, 65535).astype(np.uint16)


def batched_args(settings: dict, raw: np.ndarray) -> tuple:
    """Splat packed settings into ``render_tile_batch_packed`` argument
    order, tiling per-channel settings across the batch dim of ``raw``."""
    B = raw.shape[0]

    def tile(a):
        return np.tile(a[None], (B,) + (1,) * a.ndim)

    return (
        raw,
        tile(settings["window_start"]), tile(settings["window_end"]),
        tile(settings["family"]), tile(settings["coefficient"]),
        tile(settings["reverse"]), settings["cd_start"],
        settings["cd_end"], tile(settings["tables"]),
    )
