"""OMERO.web session middleware (≙ omero-ms-core session stores).

The reference decodes the OMERO.web Django session cookie and resolves it
to an ``omero.session_key`` request attribute through a Redis or Postgres
session store (``ImageRegionMicroserviceVerticle.java:194-212``,
``config.yaml:29-42``).  Requests without a resolvable session still flow —
ACL checks decide what they may see.

Here: a ``SessionStore`` protocol with

* :class:`StaticSessionStore` — fixed mapping / accept-all, the standalone
  and test posture;
* :class:`DjangoRedisSessionStore` — reads ``:1:django.contrib.sessions.
  cache<sid>`` entries the way OMERO.web writes them (gated on the
  ``redis`` package, absent in this image).

The resolved key travels with the request ctx exactly like the reference's
``omero.session_key`` attribute.
"""

from __future__ import annotations

import base64
import json
import pickle  # noqa: S403 — Django session payloads; trusted store only.
from typing import Mapping, Optional, Protocol

DEFAULT_COOKIE = "sessionid"  # config.yaml:29-30 session-cookie-name


class SessionStore(Protocol):
    async def get_session_key(self, session_id: str) -> Optional[str]: ...


class StaticSessionStore:
    """Fixed cookie->session-key mapping; ``accept_all`` passes the cookie
    value through as the session key (dev/standalone)."""

    def __init__(self, mapping: Optional[Mapping[str, str]] = None,
                 accept_all: bool = False):
        self.mapping = dict(mapping or {})
        self.accept_all = accept_all

    async def get_session_key(self, session_id: str) -> Optional[str]:
        if session_id in self.mapping:
            return self.mapping[session_id]
        return session_id if self.accept_all else None


def decode_django_session(payload: bytes) -> Optional[str]:
    """Extract ``omero.session_key`` ('connector' session key) from a
    Django session payload (base64(hmac:pickle) or JSON serializer)."""
    try:
        raw = base64.b64decode(payload)
        _, _, pickled = raw.partition(b":")
        data = pickle.loads(pickled)  # noqa: S301
    except Exception:
        try:
            data = json.loads(payload)
        except Exception:
            return None
    if not isinstance(data, dict):
        return None
    connector = data.get("connector")
    if isinstance(connector, dict):
        key = connector.get("omero_session_key")
        if key:
            return str(key)
    key = data.get("omero_session_key")
    return str(key) if key else None


class DjangoRedisSessionStore:
    """OMERO.web sessions out of Redis (≙ OmeroWebRedisSessionStore).
    Construction raises ImportError without the ``redis`` package."""

    def __init__(self, uri: str,
                 key_format: str = ":1:django.contrib.sessions.cache{0}"):
        import redis.asyncio as aioredis  # noqa: PLC0415
        self._client = aioredis.from_url(uri)
        self.key_format = key_format

    async def get_session_key(self, session_id: str) -> Optional[str]:
        payload = await self._client.get(self.key_format.format(session_id))
        if payload is None:
            return None
        return decode_django_session(payload)


async def resolve_session_key(store: Optional[SessionStore],
                              cookies: Mapping[str, str],
                              cookie_name: str = DEFAULT_COOKIE
                              ) -> Optional[str]:
    """Cookie jar -> omero session key (None when unresolvable)."""
    if store is None:
        return None
    session_id = cookies.get(cookie_name)
    if not session_id:
        return None
    return await store.get_session_key(session_id)
