"""OMERO.web session middleware (≙ omero-ms-core session stores).

The reference decodes the OMERO.web Django session cookie and resolves it
to an ``omero.session_key`` request attribute through a Redis or Postgres
session store (``ImageRegionMicroserviceVerticle.java:194-212``,
``config.yaml:29-42``).  With enforcement on (``session-store.required``,
the default for redis/postgres stores — matching the reference's mandatory
session handler) unresolvable cookies are rejected with 403; with it off
(static/no store) such requests still flow and ACL checks decide what
they may see.

Here: a ``SessionStore`` protocol with

* :class:`StaticSessionStore` — fixed mapping / accept-all, the standalone
  and test posture;
* :class:`DjangoRedisSessionStore` — reads ``:1:django.contrib.sessions.
  cache<sid>`` entries the way OMERO.web writes them (gated on the
  ``redis`` package, absent in this image).

The resolved key travels with the request ctx exactly like the reference's
``omero.session_key`` attribute.
"""

from __future__ import annotations

import base64
import json
import pickle  # noqa: S403 — Django session payloads; trusted store only.
from typing import Mapping, Optional, Protocol

DEFAULT_COOKIE = "sessionid"  # config.yaml:29-30 session-cookie-name


class SessionStore(Protocol):
    async def get_session_key(self, session_id: str) -> Optional[str]: ...


class StaticSessionStore:
    """Fixed cookie->session-key mapping; ``accept_all`` passes the cookie
    value through as the session key (dev/standalone)."""

    def __init__(self, mapping: Optional[Mapping[str, str]] = None,
                 accept_all: bool = False):
        self.mapping = dict(mapping or {})
        self.accept_all = accept_all

    async def get_session_key(self, session_id: str) -> Optional[str]:
        if session_id in self.mapping:
            return self.mapping[session_id]
        return session_id if self.accept_all else None


def decode_django_session(payload: bytes) -> Optional[str]:
    """Extract ``omero.session_key`` ('connector' session key) from a
    Django session payload (base64(hmac:pickle) or JSON serializer)."""
    try:
        raw = base64.b64decode(payload)
        _, _, pickled = raw.partition(b":")
        data = pickle.loads(pickled)  # noqa: S301
    except Exception:
        try:
            data = json.loads(payload)
        except Exception:
            return None
    if not isinstance(data, dict):
        return None
    connector = data.get("connector")
    if isinstance(connector, dict):
        key = connector.get("omero_session_key")
        if key:
            return str(key)
    key = data.get("omero_session_key")
    return str(key) if key else None


class DjangoRedisSessionStore:
    """OMERO.web sessions out of Redis (≙ OmeroWebRedisSessionStore).
    Construction raises ImportError without the ``redis`` package."""

    def __init__(self, uri: str,
                 key_format: str = ":1:django.contrib.sessions.cache{0}"):
        import redis.asyncio as aioredis  # noqa: PLC0415
        self._client = aioredis.from_url(uri)
        self.key_format = key_format

    async def get_session_key(self, session_id: str) -> Optional[str]:
        payload = await self._client.get(self.key_format.format(session_id))
        if payload is None:
            return None
        return decode_django_session(payload)


class DjangoPostgresSessionStore:
    """OMERO.web sessions out of the ``django_session`` table
    (≙ omero-ms-core ``OmeroWebJDBCSessionStore``): looks up the cookie's
    session key, honoring ``expire_date``, and decodes ``session_data``
    the same way as the Redis store.  Construction raises ImportError
    without an async Postgres driver (``asyncpg`` preferred, ``psycopg``
    accepted); the app factory degrades to sessions-disabled then, as it
    does for Redis.
    """

    _QUERY = ("SELECT session_data FROM django_session "
              "WHERE session_key = {ph} AND expire_date > now()")

    def __init__(self, dsn: str):
        import asyncio  # noqa: PLC0415
        try:
            import asyncpg  # noqa: PLC0415
            self._driver = "asyncpg"
            self._asyncpg = asyncpg
        except ImportError:
            import psycopg  # noqa: PLC0415
            self._driver = "psycopg"
            self._psycopg = psycopg
        self._dsn = dsn
        self._pool = None
        self._init_lock = asyncio.Lock()

    async def _fetch(self, session_id: str) -> Optional[bytes]:
        if self._driver == "asyncpg":
            if self._pool is None:
                async with self._init_lock:
                    if self._pool is None:  # lock: no double create_pool
                        self._pool = await self._asyncpg.create_pool(
                            self._dsn, min_size=1, max_size=4)
            row = await self._pool.fetchrow(
                self._QUERY.format(ph="$1"), session_id)
            return None if row is None else row[0]
        # psycopg: one autocommit connection (read-only lookups must not
        # sit idle-in-transaction on django_session), re-established after
        # any failure — there is no pool to reconnect for us.
        if self._pool is None:
            async with self._init_lock:
                if self._pool is None:
                    self._pool = await self._psycopg.AsyncConnection.connect(
                        self._dsn, autocommit=True)
        try:
            async with self._pool.cursor() as cur:
                await cur.execute(self._QUERY.format(ph="%s"), (session_id,))
                row = await cur.fetchone()
        except Exception:
            conn, self._pool = self._pool, None
            try:
                await conn.close()
            except Exception:
                pass
            raise
        return None if row is None else row[0]

    async def get_session_key(self, session_id: str) -> Optional[str]:
        payload = await self._fetch(session_id)
        if payload is None:
            return None
        if isinstance(payload, str):
            payload = payload.encode()
        return decode_django_session(payload)

    async def close(self) -> None:
        if self._pool is not None:
            await self._pool.close()
            self._pool = None


async def resolve_session_key(store: Optional[SessionStore],
                              cookies: Mapping[str, str],
                              cookie_name: str = DEFAULT_COOKIE
                              ) -> Optional[str]:
    """Cookie jar -> omero session key (None when unresolvable)."""
    if store is None:
        return None
    session_id = cookies.get(cookie_name)
    if not session_id:
        return None
    return await store.get_session_key(session_id)
