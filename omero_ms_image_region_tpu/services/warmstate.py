"""Warm-state snapshot/rehydrate: the lifecycle BETWEEN process lives.

BENCH_r05: 26 tiles/s warm vs 0.73 cold.  The disk byte cache
(``services.diskcache``) and the serialized executables
(``server.execcache``) make the expensive state durable; this module is
the engine that (a) periodically — and on SIGTERM, through the ordered
shutdown chain — writes a MANIFEST of what is hot, and (b) on boot
replays it in the background so the first interactive minute serves
warm instead of at wire+compile speed.

The manifest records three ladders of hot state:

* **byte keys** — the memory LRU's most-recent keys per named cache
  (recency is the access-frequency proxy; the bytes themselves are
  already durable in the disk tier).  Rehydrate promotes disk→memory
  through the cache stack's own read-through, so a promoted key serves
  at memory speed from request one.
* **planes** — the HBM raw cache's resident region entries: source
  coords + content digest.  Rehydrate re-reads each region from the
  pixel store and re-stages it through the EXISTING staging path
  (packed wire, digest dedup), so the pan/zoom hot set is back in HBM
  before users ask.
* **executables** — the serialized compiled-program keys
  (``server.execcache``).  Rehydrate deserializes them so the first
  group of each shape calls a compiled program, no trace/compile.

Everything is strictly best-effort: a missing/corrupt/foreign manifest
is a clean cold boot; the rehydrator yields to live traffic and aborts
on shutdown; no failure here may ever fail a request or the boot.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import List, Optional

from ..utils import telemetry

log = logging.getLogger("omero_ms_image_region_tpu.warmstate")

MANIFEST_VERSION = 1
MANIFEST_NAME = "manifest.json"

_CACHE_NAMES = ("image_region", "pixels_metadata", "shape_mask")

# Disk-tier key namespaces (services.cache.Caches.from_config).
_DISK_PREFIXES = {"image_region": "img:", "pixels_metadata": "meta:",
                  "shape_mask": "mask:"}


def restage_plane_entry(raw_cache, pixels_service, entry: dict) -> bool:
    """Re-read ONE manifest plane entry from the pixel store and stage
    it into HBM through the existing staging path (packed wire, digest
    dedup).  Shared by the boot rehydrator and the rolling-drain
    pre-stager (``parallel.fleet`` hands a draining member's shard
    manifest to its ring successor through this).  Returns False on a
    malformed entry; read errors propagate to the caller's guard."""
    from ..io.devicecache import region_key
    from ..server.region import RegionDef

    try:
        image_id, z, t, level, region, channels = entry["key"]
        key = region_key(int(image_id), int(z), int(t), int(level),
                         tuple(int(v) for v in region),
                         tuple(int(c) for c in channels))
    except (KeyError, TypeError, ValueError):
        return False
    if key in raw_cache:
        return True

    def load():
        import numpy as np
        src = pixels_service.get_pixel_source(key[0])
        x, y, w, h = key[4]
        sub = RegionDef(x, y, w, h)
        return np.stack([
            src.get_region(key[1], c, key[2], sub, key[3])
            for c in key[5]
        ])

    # Carry the entry's recorded routing identity onto the receiving
    # cache: a restaged plane that loses its route would fall back to
    # key-repr spreading on the NEXT drain's handoff, silently handing
    # planes to ring members that will never serve their requests.
    raw_cache.get_or_load(key, load, route_key=entry.get("route"))
    return True


def _load_manifest(path: str) -> Optional[dict]:
    """Parse-or-None: a truncated, corrupt or non-JSON manifest is a
    cold boot, never an exception."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or doc.get("version") != MANIFEST_VERSION:
        return None
    return doc


class WarmStateManager:
    """Snapshot timer + boot rehydrator for one device-owning process.

    ``services`` is duck-typed (``server.handler.ImageRegionServices``):
    the manager reads its caches, raw cache, renderer (exec cache) and
    pixel service; it never holds the request path.
    """

    def __init__(self, directory: str, services,
                 snapshot_interval_s: float = 60.0,
                 snapshot_top_k: int = 512,
                 max_plane_entries: int = 256,
                 rehydrate_concurrency: int = 2):
        self.directory = directory
        self.services = services
        self.snapshot_interval_s = snapshot_interval_s
        self.snapshot_top_k = snapshot_top_k
        self.max_plane_entries = max_plane_entries
        self.rehydrate_concurrency = max(1, rehydrate_concurrency)
        self._stop = threading.Event()
        self._snapshot_lock = threading.Lock()
        self._timer_thread: Optional[threading.Thread] = None
        self._rehydrate_thread: Optional[threading.Thread] = None
        # Brownout ladder hook (server.pressure "pause_snapshots"):
        # while paused the periodic timer skips its snapshot — the
        # manifest write is disk + CPU work a drowning process can
        # defer.  Explicit snapshots (SIGTERM chain, /debug/warmstate,
        # drains) still run: those are the moments the manifest is FOR.
        self.paused = False

    # ------------------------------------------------------------ start

    def start(self, rehydrate: bool = True) -> None:
        """Kick the boot rehydrator and the periodic snapshot timer
        (both daemon threads; both end at ``close``)."""
        os.makedirs(self.directory, exist_ok=True)
        if rehydrate:
            self._rehydrate_thread = threading.Thread(
                target=self._rehydrate_guarded,
                name="warmstate-rehydrate", daemon=True)
            self._rehydrate_thread.start()
        if self.snapshot_interval_s > 0:
            self._timer_thread = threading.Thread(
                target=self._timer_loop, name="warmstate-snapshot",
                daemon=True)
            self._timer_thread.start()

    def close(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        for t in (self._rehydrate_thread, self._timer_thread):
            if t is not None and t.is_alive():
                t.join(timeout=timeout_s)

    def _timer_loop(self) -> None:
        while not self._stop.wait(self.snapshot_interval_s):
            if self.paused:
                continue
            try:
                self.snapshot_now()
            except Exception:
                # snapshot_now is internally guarded; this is the
                # thread-never-dies belt over those braces.
                log.warning("periodic warm-state snapshot failed",
                            exc_info=True)

    # --------------------------------------------------------- snapshot

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST_NAME)

    def _collect_manifest(self) -> dict:
        doc = {"version": MANIFEST_VERSION, "ts": round(time.time(), 3),
               "byte_keys": {}, "planes": [], "executables": []}
        caches = getattr(self.services, "caches", None)
        disk_keys: Optional[List[str]] = None
        for name in _CACHE_NAMES:
            stack = getattr(caches, name, None)
            tiers = getattr(stack, "tiers", ())
            keys: List[str] = []
            if tiers:
                recency = getattr(tiers[0], "keys_by_recency", None)
                if recency is not None:
                    keys = recency(self.snapshot_top_k)
            if not keys:
                # The native C++ memory tier has no key enumeration;
                # fall back to the durable tier's own recency order
                # (mtime MRU-first — reads bump it, so this IS the
                # hot set as the disk saw it).
                disk = getattr(caches, "disk", None)
                if disk is not None:
                    if disk_keys is None:
                        disk_keys = disk.keys_sync()
                    prefix = _DISK_PREFIXES[name]
                    keys = [k[len(prefix):] for k in disk_keys
                            if k.startswith(prefix)][
                                :self.snapshot_top_k]
            doc["byte_keys"][name] = keys
        raw_cache = getattr(self.services, "raw_cache", None)
        if raw_cache is not None and hasattr(raw_cache,
                                             "snapshot_entries"):
            doc["planes"] = raw_cache.snapshot_entries(
                self.max_plane_entries)
        exec_cache = getattr(getattr(self.services, "renderer", None),
                             "exec_cache", None)
        if exec_cache is not None:
            doc["fingerprint"] = exec_cache.fingerprint()
            doc["executables"] = exec_cache.stored_keys()
        # The perf sentinel's learned latency baselines ride the same
        # manifest: a restart must not re-learn "normal" from scratch
        # (a regression deployed WITH the restart would become the new
        # baseline before the sentinel could see it).  Lazy import —
        # services must not import server at module scope.
        from ..server import sentinel as sentinel_mod
        engine = sentinel_mod.active()
        if engine is not None:
            baselines = engine.export_baseline()
            if baselines.get("baselines"):
                doc["sentinel"] = baselines
        return doc

    def snapshot_now(self) -> Optional[str]:
        """Write the manifest atomically; returns the path or None.
        Never raises — it runs inside signal-time shutdown chains and
        the periodic timer alike.  Serialized against itself (the
        SIGTERM chain may race the timer)."""
        t0 = time.perf_counter()
        with self._snapshot_lock:
            try:
                doc = self._collect_manifest()
                os.makedirs(self.directory, exist_ok=True)
                path = self.manifest_path
                tmp = path + f".tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(doc, f)
                os.replace(tmp, path)
            except Exception:
                telemetry.PERSIST.count_snapshot(0.0, error=True)
                log.warning("warm-state snapshot failed", exc_info=True)
                return None
        duration_ms = (time.perf_counter() - t0) * 1000.0
        telemetry.PERSIST.count_snapshot(duration_ms)
        telemetry.FLIGHT.record(
            "warmstate.snapshot",
            keys=sum(len(v) for v in doc["byte_keys"].values()),
            planes=len(doc["planes"]),
            executables=len(doc["executables"]),
            ms=round(duration_ms, 1))
        return path

    # -------------------------------------------------------- rehydrate

    def _yield_to_live_load(self) -> None:
        """Best-effort politeness: while serving traffic is queued or
        in flight, the rehydrator waits — briefly and boundedly, so a
        continuously loaded boot still trickles warm state in instead
        of starving forever."""
        renderer = getattr(self.services, "renderer", None)
        depth = getattr(renderer, "queue_depth", None)
        inflight = getattr(renderer, "inflight", None)
        if depth is None:
            return
        waited = 0.0
        while not self._stop.is_set() and waited < 2.0:
            busy = depth() > 0 or (inflight is not None
                                   and inflight() > 0)
            if not busy:
                return
            time.sleep(0.05)
            waited += 0.05

    def _rehydrate_guarded(self) -> None:
        t0 = time.perf_counter()
        try:
            self._rehydrate()
        except Exception:
            # Strictly best-effort: a rehydrate bug is a slow first
            # minute, never a failed boot.
            telemetry.PERSIST.rehydrate_end(
                (time.perf_counter() - t0) * 1000.0, aborted=True)
            log.warning("warm-state rehydrate failed; serving cold",
                        exc_info=True)

    def _rehydrate(self) -> None:
        doc = _load_manifest(self.manifest_path)
        if doc is None:
            telemetry.PERSIST.rehydrate_begin(0)
            telemetry.PERSIST.rehydrate_end(0.0)
            log.info("no usable warm-state manifest; cold boot")
            return
        exec_cache = getattr(getattr(self.services, "renderer", None),
                             "exec_cache", None)
        exec_keys = list(doc.get("executables") or ())
        if exec_cache is not None and doc.get("fingerprint") not in (
                None, exec_cache.fingerprint()):
            # Different jax/jaxlib/device than the life that wrote the
            # manifest: its executables cannot load here.  Bytes and
            # planes are hardware-independent and still replay.
            log.info("warm-state manifest fingerprint differs; "
                     "skipping executable rehydrate")
            exec_keys = []
        # Sentinel baseline rehydrate first — it is a dict copy, not
        # I/O, and the engine should know "normal" before the first
        # post-boot windows close.  Best-effort like everything here.
        sentinel_doc = doc.get("sentinel")
        if sentinel_doc:
            try:
                from ..server import sentinel as sentinel_mod
                engine = sentinel_mod.active()
                if engine is not None:
                    n = engine.load_baseline(sentinel_doc)
                    if n:
                        log.info("restored %d sentinel baselines", n)
            except Exception:
                log.warning("sentinel baseline rehydrate failed",
                            exc_info=True)
        byte_items = [(name, key)
                      for name in _CACHE_NAMES
                      for key in (doc.get("byte_keys") or {}).get(name,
                                                                  ())]
        plane_items = list(doc.get("planes") or ())
        exec_items = (len(exec_keys) if exec_cache is not None else 0)
        total = len(byte_items) + len(plane_items) + exec_items
        telemetry.PERSIST.rehydrate_begin(total)
        telemetry.FLIGHT.record("warmstate.rehydrate.start",
                                items=total)
        t0 = time.perf_counter()
        aborted = False

        # 1. Executables first: they are what the first GROUP of each
        # shape needs, and deserializing is milliseconds against the
        # seconds a compile costs.  One progress item per manifest key,
        # loaded or not, so items_done always converges on items_total
        # (the rolling-deploy runbook waits for "done N/N").
        if exec_items:
            n = exec_cache.preload(exec_keys)
            for _ in range(n):
                telemetry.PERSIST.rehydrate_step("executable")
            for _ in range(exec_items - n):
                telemetry.PERSIST.rehydrate_step("executable",
                                                 error=True)

        # 2. Disk -> memory byte promotion: the stack's own
        # read-through back-fills the memory tier on a disk hit, so a
        # promoted key's next request is a memory hit.
        caches = getattr(self.services, "caches", None)
        for name, key in byte_items:
            if self._stop.is_set():
                aborted = True
                break
            self._yield_to_live_load()
            try:
                value = self._promote_byte(caches, name, key)
                telemetry.PERSIST.rehydrate_step(
                    "byte", nbytes=len(value) if value else 0,
                    error=value is None)
            except Exception:
                telemetry.PERSIST.rehydrate_step("byte", error=True)

        # 3. Plane re-stage to HBM through the existing staging path
        # (packed wire + digest dedup), bounded by the concurrency
        # knob — staging is link work and must not saturate the
        # host->device wire under live load.
        if plane_items and not aborted and not self._stop.is_set():
            aborted = self._restage_planes(plane_items) or aborted
        telemetry.PERSIST.rehydrate_end(
            (time.perf_counter() - t0) * 1000.0, aborted=aborted)
        telemetry.FLIGHT.record("warmstate.rehydrate.done",
                                aborted=aborted,
                                ms=round((time.perf_counter() - t0)
                                         * 1000.0, 1))
        log.info("warm-state rehydrate %s (%d items)",
                 "aborted" if aborted else "complete", total)

    def _promote_byte(self, caches, name: str,
                      key: str) -> Optional[bytes]:
        """Disk tier -> memory tier for one key; returns the bytes or
        None (not durable / corrupt — both fine, the next request
        re-renders)."""
        stack = getattr(caches, name, None)
        tiers = getattr(stack, "tiers", ())
        memory = tiers[0] if tiers else None
        disk = None
        for tier in tiers:
            inner = getattr(tier, "inner", None)
            if inner is not None and hasattr(inner, "get_sync"):
                disk = tier
                break
        if memory is None or disk is None:
            return None
        if not isinstance(key, str):
            return None
        value = disk.inner.get_sync(disk.prefix + key)
        if value is None:
            return None
        set_sync = getattr(memory, "set_sync", None)
        if set_sync is None:
            return None
        set_sync(key, value)
        return value

    def _restage_planes(self, plane_items: List[dict]) -> bool:
        """Re-read manifest regions from the pixel store and stage them
        back into HBM (worker pool of ``rehydrate_concurrency``).
        Returns True when aborted by shutdown."""
        import concurrent.futures as cf

        raw_cache = getattr(self.services, "raw_cache", None)
        pixels_service = getattr(self.services, "pixels_service", None)
        if raw_cache is None or pixels_service is None:
            for _ in plane_items:
                telemetry.PERSIST.rehydrate_step("plane", error=True)
            return False

        def restage(entry: dict) -> bool:
            return restage_plane_entry(raw_cache, pixels_service,
                                       entry)

        aborted = False
        with cf.ThreadPoolExecutor(
                max_workers=self.rehydrate_concurrency,
                thread_name_prefix="warmstate-stage") as pool:
            pending = []
            for entry in plane_items:
                if self._stop.is_set():
                    aborted = True
                    break
                self._yield_to_live_load()
                pending.append(pool.submit(restage, entry))
            for fut in pending:
                try:
                    ok = fut.result()
                    telemetry.PERSIST.rehydrate_step("plane",
                                                     error=not ok)
                except Exception:
                    telemetry.PERSIST.rehydrate_step("plane",
                                                     error=True)
        return aborted
