"""Crash-safe on-disk byte-cache tier: rendered bytes that survive the
process.

BENCH_r05 shows the service is two systems — 26 tiles/s warm vs 0.73
cold — because a restart drops every tier that makes it fast.  The
reference survives restarts through its Redis/Hazelcast shared-state
split (SURVEY.md §5); this image has no Redis, so the durable tier is
the local disk: a content-addressed, size-bounded file store slotted
into the ``services.cache`` chain between the in-memory LRU and the
(optional) Redis client.  Rendered tiles, masks and metadata memos
written here are served after a deploy, a supervisor respawn or a
crash without a wire fetch or a device dispatch.

Design constraints, in order:

* **Crash-safe**: every write is tmp + ``os.replace`` into a sharded
  directory, so a torn write never leaves a half entry under a live
  name; every entry carries a BLAKE2b checksum over key + value, so a
  torn BLOCK (or a flipped bit, or an alien file) reads as a miss —
  never as poisoned bytes served to a client.
* **Never on the hot path**: ``set`` is write-behind — it enqueues onto
  a bounded queue drained by one worker thread and returns; a full
  queue drops the write (counted) rather than blocking a render.
  ``get`` runs the file read on a worker thread via the async face.
* **Size-bounded**: a byte budget enforced by the worker — when the
  tracked size passes ``max_bytes`` it scans entry mtimes and evicts
  oldest-first down to a low-water mark.  Reads bump mtime (the LRU
  touch), so the scan order IS recency order.
* **Degrades, never fails**: every filesystem error is a miss or a
  dropped write plus a counter (``telemetry.PERSIST``); a read-only or
  full disk turns the tier off-shaped, not the service.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import queue
import struct
import threading
from typing import List, Optional, Tuple

from ..utils import telemetry

log = logging.getLogger("omero_ms_image_region_tpu.diskcache")

# Entry format: MAGIC | u16 key_len | u32 value_len | blake2b-16 over
# (key_bytes + value) | key_bytes | value.  The stored key is verified
# against the requested key on read — a (vanishingly unlikely) digest
# filename collision must alias to a miss, not to another key's bytes.
_MAGIC = b"IRB1"
_HEADER = struct.Struct("<4sHI16s")

# Default eviction low-water mark: evict down to this fraction of
# max_bytes so each over-budget episode frees a batch, not one file.
_LOW_WATER = 0.9


def _digest(key: bytes, value: bytes) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(key)
    h.update(value)
    return h.digest()


def encode_entry(key: str, value: bytes) -> bytes:
    kb = key.encode()
    return (_HEADER.pack(_MAGIC, len(kb), len(value),
                         _digest(kb, value)) + kb + value)


def decode_entry(blob: bytes, key: str) -> Optional[bytes]:
    """Value bytes, or None when the blob fails ANY integrity check
    (wrong magic, truncation, trailing garbage, checksum mismatch,
    foreign key).  Never raises on hostile content."""
    try:
        if len(blob) < _HEADER.size:
            return None
        magic, key_len, value_len, digest = _HEADER.unpack_from(blob)
        if magic != _MAGIC:
            return None
        end = _HEADER.size + key_len + value_len
        if end != len(blob):
            return None
        kb = blob[_HEADER.size:_HEADER.size + key_len]
        value = blob[_HEADER.size + key_len:end]
        if kb != key.encode():
            return None
        if _digest(kb, value) != digest:
            return None
        return value
    except Exception:
        return None


class DiskByteCache:
    """Crash-safe content-addressed disk tier for the byte-cache chain.

    The sync face (``get_sync``/``set_sync``) is what the write-behind
    worker, tests and the boot rehydrator use; the async face matches
    the ``CacheTier`` protocol (``get`` off-loads the file read,
    ``set`` enqueues and returns).
    """

    SHARD_CHARS = 2          # 256 shard dirs
    QUEUE_DEPTH = 256        # pending write-behind entries
    # Gauge-publish coalescing: the write-behind worker publishes the
    # size gauges at most this often (plus once when its queue drains),
    # instead of taking the telemetry lock on every write — measured
    # contention against request threads flushing their own counters.
    PUBLISH_INTERVAL_S = 0.5

    def __init__(self, directory: str,
                 max_bytes: int = 1024 * 1024 * 1024,
                 sync_writes: bool = False):
        self.directory = directory
        self.max_bytes = max_bytes
        # sync_writes: write inline instead of behind the queue — the
        # deterministic mode tests and the snapshot path use.
        self.sync_writes = sync_writes
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._size_lock = threading.Lock()
        self._bytes = 0
        self._entries = 0
        self._scanned = False
        self._last_publish = 0.0
        self._queue: "queue.Queue[Optional[Tuple[str, bytes]]]" = \
            queue.Queue(maxsize=self.QUEUE_DEPTH)
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        self._closed = False

    # ----------------------------------------------------------- paths

    def _path_of(self, key: str) -> str:
        name = hashlib.blake2b(key.encode(), digest_size=16).hexdigest()
        return os.path.join(self.directory, name[:self.SHARD_CHARS],
                            name + ".irb")

    # ----------------------------------------------------------- sizing

    def _scan_size(self) -> None:
        """One-time startup accounting of what a previous life left on
        disk (runs on the worker thread, or lazily on first use)."""
        total = entries = 0
        try:
            with os.scandir(self.directory) as shards:
                for shard in shards:
                    if not shard.is_dir():
                        continue
                    with os.scandir(shard.path) as files:
                        for f in files:
                            if not f.name.endswith(".irb"):
                                continue
                            try:
                                total += f.stat().st_size
                                entries += 1
                            except OSError:
                                pass
        except OSError:
            pass
        with self._size_lock:
            self._bytes += total
            self._entries += entries
        self._publish_size()

    def _ensure_scanned(self) -> None:
        # Claim-then-scan: the claim flips INSIDE the lock, so two
        # concurrent first touches can never both run the scan and
        # double-count the prior life's entries (phantom bytes would
        # evict exactly the warm set this tier exists to preserve).
        with self._size_lock:
            if self._scanned:
                return
            self._scanned = True
        self._scan_size()

    def _publish_size(self, force: bool = False) -> None:
        import time as _time
        now = _time.monotonic()
        with self._size_lock:
            if not force and (now - self._last_publish
                              < self.PUBLISH_INTERVAL_S):
                return
            self._last_publish = now
            telemetry.PERSIST.set_disk_size(self._bytes, self._entries)

    @property
    def size_bytes(self) -> int:
        with self._size_lock:
            return self._bytes

    def __len__(self) -> int:
        with self._size_lock:
            return self._entries

    # ------------------------------------------------------------ reads

    def get_sync(self, key: str) -> Optional[bytes]:
        # One-time: a restarted process must account (and publish) the
        # previous life's entries even if it only ever READS them.
        self._ensure_scanned()
        path = self._path_of(key)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            self.misses += 1
            return None
        value = decode_entry(blob, key)
        if value is None:
            # Corrupt (or foreign) entry: count it, remove it so the
            # next write can replace it, and serve a MISS — the caller
            # re-renders from source; nothing poisoned ever leaves.
            self.misses += 1
            telemetry.PERSIST.count_disk_corrupt()
            self._unlink(path)
            return None
        self.hits += 1
        try:
            # The LRU touch: eviction scans mtime oldest-first.
            os.utime(path)
        except OSError:
            pass
        return value

    # ----------------------------------------------------------- writes

    def set_sync(self, key: str, value: bytes) -> None:
        """Atomic write: tmp file in the target shard, then
        ``os.replace`` — a crash mid-write leaves only a tmp file (a
        later eviction scan sweeps it), never a half entry."""
        if len(value) > self.max_bytes:
            return
        # Account a previous life's leftovers BEFORE this write lands,
        # or the scan would double-count it.
        self._ensure_scanned()
        path = self._path_of(key)
        shard = os.path.dirname(path)
        tmp = path + f".tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            os.makedirs(shard, exist_ok=True)
            blob = encode_entry(key, value)
            try:
                old_size = os.path.getsize(path)
            except OSError:
                old_size = None
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError as e:
            telemetry.PERSIST.count_disk_write(error=True)
            self._unlink(tmp)
            log.warning("disk cache write failed, degrading: %s", e)
            return
        telemetry.PERSIST.count_disk_write()
        with self._size_lock:
            self._bytes += len(blob) - (old_size or 0)
            if old_size is None:
                self._entries += 1
        self._evict_if_needed()
        self._publish_size()

    def _unlink(self, path: str) -> None:
        try:
            size = os.path.getsize(path)
            os.unlink(path)
        except OSError:
            return
        with self._size_lock:
            self._bytes = max(0, self._bytes - size)
            self._entries = max(0, self._entries - 1)

    # --------------------------------------------------------- eviction

    def _entry_mtimes(self) -> List[Tuple[float, str, int]]:
        out = []
        try:
            with os.scandir(self.directory) as shards:
                for shard in shards:
                    if not shard.is_dir():
                        continue
                    with os.scandir(shard.path) as files:
                        for f in files:
                            try:
                                st = f.stat()
                            except OSError:
                                continue
                            if f.name.endswith(".irb"):
                                out.append((st.st_mtime, f.path,
                                            st.st_size))
                            elif ".tmp." in f.name:
                                # Orphaned tmp from a crash mid-write.
                                try:
                                    os.unlink(f.path)
                                except OSError:
                                    pass
        except OSError:
            pass
        out.sort()
        return out

    def _evict_if_needed(self) -> None:
        with self._size_lock:
            over = self._bytes > self.max_bytes
        if not over:
            return
        self._evict_to(int(self.max_bytes * _LOW_WATER))

    def evict_to_fraction(self, frac: float) -> None:
        """Brownout eviction (server.pressure "evict_caches"): walk the
        tier toward ``frac`` of budget NOW, oldest-first — the chosen,
        early form of the per-write eviction above, run while the disk
        is merely filling instead of when a write finds it full."""
        self._ensure_scanned()
        target = max(0, int(self.max_bytes * frac))
        with self._size_lock:
            over = self._bytes > target
        if over:
            self._evict_to(target)

    def _evict_to(self, target: int) -> None:
        for _mtime, path, size in self._entry_mtimes():
            with self._size_lock:
                if self._bytes <= target:
                    break
            try:
                os.unlink(path)
            except OSError:
                continue
            with self._size_lock:
                self._bytes = max(0, self._bytes - size)
                self._entries = max(0, self._entries - 1)
            self.evictions += 1
            telemetry.FLIGHT.record("diskcache.evict", bytes=size)
        self._publish_size()

    # ------------------------------------------------------ write-behind

    def _worker_loop(self) -> None:
        self._ensure_scanned()
        while True:
            item = self._queue.get()
            if item is None:
                return
            key, value = item
            try:
                self.set_sync(key, value)
                if self._queue.empty():
                    # Burst drained: land the coalesced gauges now
                    # rather than waiting out the publish interval.
                    self._publish_size(force=True)
            except Exception:
                # set_sync already degrades on OSError; this catches
                # anything else so the worker thread never dies and
                # silently turns every later set into a dropped write.
                telemetry.PERSIST.count_disk_write(error=True)
                log.warning("disk cache write-behind failed",
                            exc_info=True)

    def _ensure_worker(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        with self._worker_lock:
            if self._worker is not None and self._worker.is_alive():
                return
            if self._closed:
                return
            self._worker = threading.Thread(
                target=self._worker_loop, name="diskcache-writer",
                daemon=True)
            self._worker.start()

    def flush(self, timeout_s: float = 5.0) -> None:
        """Drain pending write-behind entries (shutdown + tests)."""
        import time as _time
        deadline = _time.monotonic() + timeout_s
        while not self._queue.empty():
            if _time.monotonic() >= deadline:
                return
            _time.sleep(0.01)

    def close(self) -> None:
        self._closed = True
        worker = self._worker
        if worker is not None and worker.is_alive():
            self.flush()
            self._queue.put(None)
            worker.join(timeout=5.0)

    # ------------------------------------------------------- async face

    async def get(self, key: str) -> Optional[bytes]:
        return await asyncio.to_thread(self.get_sync, key)

    async def contains(self, key: str) -> bool:
        """Existence probe — one stat, no read/verify/mtime effect
        (the explain plane's dry-run view).  A corrupt entry may read
        present here and still degrade to an honest MISS on the real
        ``get``; a residency HINT may be optimistic, a byte path may
        not."""
        return await asyncio.to_thread(os.path.exists,
                                       self._path_of(key))

    async def set(self, key: str, value: bytes) -> None:
        if self.sync_writes:
            await asyncio.to_thread(self.set_sync, key, value)
            return
        self._ensure_worker()
        try:
            self._queue.put_nowait((key, value))
        except queue.Full:
            # Never block a render behind disk I/O: drop and count.
            telemetry.PERSIST.count_disk_write(dropped=True)

    # ------------------------------------------------------- enumeration

    def keys_sync(self, limit: int = 0) -> List[str]:
        """Stored keys, most-recently-used first (entry headers carry
        the key verbatim) — the snapshot engine's view of what is
        durable.  ``limit`` 0 = all."""
        out: List[str] = []
        for _mtime, path, _size in reversed(self._entry_mtimes()):
            try:
                with open(path, "rb") as f:
                    head = f.read(_HEADER.size)
                    if len(head) < _HEADER.size:
                        continue
                    magic, key_len, _vlen, _dig = _HEADER.unpack(head)
                    if magic != _MAGIC:
                        continue
                    kb = f.read(key_len)
                if len(kb) == key_len:
                    out.append(kb.decode("utf-8", "replace"))
            except OSError:
                continue
            if limit and len(out) >= limit:
                break
        return out
