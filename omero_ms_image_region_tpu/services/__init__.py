"""Host-side services: caches, metadata/ACL, session stores.

The analogue of the reference's L0 infrastructure (omero-ms-core Redis cache
verticle, OMERO backbone metadata/ACL event-bus services, OMERO.web session
stores; SURVEY.md §2b) — re-expressed as asyncio-friendly Python services
with pluggable backends.
"""

from .cache import CacheConfig, CacheStack, MemoryLRUCache, make_cache
from .metadata import CanReadMemo, LocalMetadataService, MetadataService
from .sessions import SessionStore, StaticSessionStore
