"""Open-loop million-session load model: arrivals that do not wait.

Every bench leg before this one was CLOSED-loop — a fixed set of
worker coroutines that issue a request, await the response, think,
and only then issue the next.  A closed loop is self-throttling: when
the service slows down, the offered load slows down with it, so the
measured latency curve flattens exactly where a real open system
(millions of independent browsers that do NOT coordinate their
clicks) would hit queueing collapse.  The reference survives behind
nginx because capacity was provisioned for the open arrival process,
not the closed one (PAPER.md L0/L5); this module makes that arrival
process a measurable, deterministic object:

* :class:`LoadModel` — a seeded generator of 10^4..10^6 simulated
  viewer SESSIONS: heavy-tailed (lognormal) think times and session
  lengths, per-session viewport trajectories on the same pan/zoom
  lattice the PR 10 viewport model predicts (runs of constant tile
  velocity with occasional turns and zoom level changes), a diurnal
  intensity warp (sessions bunch toward the peak of a half-sine
  "day"), and a configurable interactive/bulk/mask request-class mix.
  Generation is lazy (``iter_events`` is a heap-merge over per-session
  streams) so a million-session stream never materializes at once,
  and deterministic by construction — same seed, same byte-identical
  event stream (pinned in tests/test_loadmodel.py).
* :func:`run_open_loop` — fires each arrival AT ITS SCHEDULED TIME
  regardless of completions (``asyncio.create_task`` per arrival,
  never awaited before the next fires).  Arrivals behind schedule
  fire immediately and are counted (``late``) — the open-loop
  integrity signal.
* :func:`run_closed_loop` — the SAME arrival list executed by a fixed
  worker pool that waits for completions: the flattering A/B leg.
  ``bench.py --smoke --capacity`` pins ``closed p99 < open p99`` past
  the knee so future bench legs cannot quietly revert to closed-loop
  arrivals and report a collapse-free curve.
* :func:`find_knee` — the capacity knee of a measured
  latency-vs-offered-load curve: the highest offered load whose p99
  still meets the SLO and whose shed rate stays under the bound.

The model DRIVES a real in-process fleet (``bench_capacity_smoke``,
the elasticity drill in tests/test_autoscaler.py); nothing here
imports device code.
"""

from __future__ import annotations

import asyncio
import bisect
import heapq
import math
import random
import time
from dataclasses import dataclass, field
from typing import (Awaitable, Callable, Dict, Iterable, Iterator, List,
                    Optional, Sequence, Tuple)

from ..utils import telemetry

# The request-class vocabulary — the SAME classification the QoS tier
# serves (pressure.is_bulk: interactive tile vs bulk full-plane), plus
# the mask endpoint (QoS-classed interactive, but its own route and
# fairness surface — the PR 10 follow-on this PR closes).
CLASSES = ("interactive", "bulk", "mask", "pyramid", "animation")

# Pan velocities a viewer trajectory may run with (same lattice steps
# the viewport predictor extrapolates).
_VELOCITIES = ((1, 0), (0, 1), (-1, 0), (0, -1), (1, 1), (-1, -1))


@dataclass(frozen=True)
class Arrival:
    """One scheduled request of one simulated session.

    ``t`` is the offset in seconds from the window start on the
    model's NATURAL timeline; :meth:`LoadModel.schedule` rescales it
    to a target offered rate.  ``x``/``y``/``level`` address the tile
    lattice for interactive arrivals (bulk renders the full plane;
    masks address ``shape_id = step``-derived ids).  ``image`` is the
    POPULARITY RANK of the image the session browses (0 = hottest),
    drawn once per session from the model's zipf skew — the hot-key
    storm input (``bench.py --smoke --hotkey``); 0 for every arrival
    when the model is unskewed (the pre-skew single-image stream)."""

    t: float
    session: str
    cls: str
    step: int
    x: int = 0
    y: int = 0
    level: int = 0
    image: int = 0


class LoadModel:
    """Deterministic seeded open-loop session generator."""

    def __init__(self, viewers: int = 100, seed: int = 1234,
                 duration_s: float = 60.0, grid: int = 8,
                 think_time_median_ms: float = 350.0,
                 think_time_sigma: float = 1.0,
                 session_length_median: float = 24.0,
                 session_length_sigma: float = 1.2,
                 diurnal_amplitude: float = 0.6,
                 bulk_fraction: float = 0.0,
                 mask_fraction: float = 0.0,
                 pyramid_fraction: float = 0.0,
                 animation_fraction: float = 0.0,
                 zoom_fraction: float = 0.05,
                 max_level: int = 0,
                 skew: float = 0.0,
                 image_population: int = 1):
        if viewers < 1:
            raise ValueError("loadmodel viewers must be >= 1")
        if duration_s <= 0:
            raise ValueError("loadmodel duration_s must be > 0")
        if grid < 1:
            raise ValueError("loadmodel grid must be >= 1")
        if think_time_median_ms <= 0 or session_length_median <= 0:
            raise ValueError("loadmodel medians must be > 0")
        if think_time_sigma < 0 or session_length_sigma < 0:
            raise ValueError("loadmodel sigmas must be >= 0")
        if not 0.0 <= diurnal_amplitude < 1.0:
            raise ValueError(
                "loadmodel diurnal_amplitude must be in [0, 1)")
        for name, frac in (("bulk_fraction", bulk_fraction),
                           ("mask_fraction", mask_fraction),
                           ("pyramid_fraction", pyramid_fraction),
                           ("animation_fraction", animation_fraction),
                           ("zoom_fraction", zoom_fraction)):
            if not 0.0 <= frac <= 1.0:
                raise ValueError(f"loadmodel {name} must be in [0, 1]")
        if (bulk_fraction + mask_fraction + pyramid_fraction
                + animation_fraction) > 1.0:
            raise ValueError("loadmodel class fractions (bulk + mask + "
                             "pyramid + animation) must sum to <= 1")
        if skew < 0:
            raise ValueError("loadmodel skew must be >= 0")
        if image_population < 1:
            raise ValueError("loadmodel image_population must be >= 1")
        self.viewers = int(viewers)
        self.seed = int(seed)
        self.duration_s = float(duration_s)
        self.grid = int(grid)
        self.think_time_median_ms = float(think_time_median_ms)
        self.think_time_sigma = float(think_time_sigma)
        self.session_length_median = float(session_length_median)
        self.session_length_sigma = float(session_length_sigma)
        self.diurnal_amplitude = float(diurnal_amplitude)
        self.bulk_fraction = float(bulk_fraction)
        self.mask_fraction = float(mask_fraction)
        self.pyramid_fraction = float(pyramid_fraction)
        self.animation_fraction = float(animation_fraction)
        self.zoom_fraction = float(zoom_fraction)
        self.max_level = int(max_level)
        self.skew = float(skew)
        self.image_population = int(image_population)
        # Popularity CDF over image ranks: zipf weights 1/(k+1)^s
        # (rank 0 hottest; s=0 degenerates to uniform).  Precomputed
        # once — a million sessions bisect the same table.
        self._image_cdf: Optional[List[float]] = None
        if self.image_population > 1:
            weights = [1.0 / (k + 1) ** self.skew
                       for k in range(self.image_population)]
            total = sum(weights)
            acc, cdf = 0.0, []
            for w in weights:
                acc += w / total
                cdf.append(acc)
            cdf[-1] = 1.0
            self._image_cdf = cdf

    @classmethod
    def from_config(cls, config, **structural) -> "LoadModel":
        """Build from a ``loadmodel:`` config block
        (``server.config.LoadModelConfig`` — the validated knob
        surface operators tune); ``structural`` carries the
        deployment-shape parameters the block deliberately does not
        own (duration_s, grid, max_level) plus any per-leg overrides
        (a capacity sweep pins viewers/diurnal for determinism)."""
        kwargs = dict(
            viewers=config.viewers, seed=config.seed,
            think_time_median_ms=config.think_time_median_ms,
            think_time_sigma=config.think_time_sigma,
            session_length_median=config.session_length_median,
            session_length_sigma=config.session_length_sigma,
            diurnal_amplitude=config.diurnal_amplitude,
            bulk_fraction=config.bulk_fraction,
            mask_fraction=config.mask_fraction,
            pyramid_fraction=config.pyramid_fraction,
            animation_fraction=config.animation_fraction,
            zoom_fraction=config.zoom_fraction,
            skew=config.skew,
            image_population=config.image_population)
        kwargs.update(structural)
        return cls(**kwargs)

    # ------------------------------------------------------- diurnal warp

    def _intensity_cdf(self, t: float) -> float:
        """Cumulative mass of the diurnal intensity
        ``1 + A * sin(pi * t / T)`` on [0, T] — a half-sine "day"
        rising to its peak at T/2 and falling back, so one run
        exercises a full ramp-up AND ramp-down (what the elasticity
        drill needs from a single window)."""
        T, A = self.duration_s, self.diurnal_amplitude
        mass = t + A * T / math.pi * (1.0 - math.cos(math.pi * t / T))
        total = T + 2.0 * A * T / math.pi
        return mass / total

    def _warp(self, u: float) -> float:
        """Inverse-CDF of the diurnal intensity: a uniform position
        ``u`` in [0, 1) -> a session start time in [0, T) bunched
        toward the diurnal peak.  Deterministic bisection (no
        closed-form inverse; 40 halvings are exact far past float
        resolution)."""
        lo, hi = 0.0, self.duration_s
        for _ in range(40):
            mid = (lo + hi) / 2.0
            if self._intensity_cdf(mid) < u:
                lo = mid
            else:
                hi = mid
        return (lo + hi) / 2.0

    # --------------------------------------------------------- generation

    def _session_stream(self, i: int) -> Iterator[Arrival]:
        """One viewer's arrivals, time-ordered.  Every draw comes from
        a per-session ``random.Random`` seeded from (model seed, i) so
        the stream is identical run to run AND independent of how many
        other sessions are interleaved around it."""
        rng = random.Random((self.seed << 20) ^ i)
        session = f"sim-{i}"
        # The session's image rank comes from a SEPARATE derived RNG:
        # turning the skew knob must not shift the trajectory/timing
        # stream (pinned: same seed -> byte-identical arrivals modulo
        # the ``image`` field), and population==1 consumes no draw at
        # all so the pre-skew stream stays bit-exact.
        image = 0
        if self._image_cdf is not None:
            u = random.Random(f"img|{self.seed}|{i}").random()
            image = bisect.bisect_left(self._image_cdf, u)
        t = self._warp(rng.random())
        n = max(1, int(rng.lognormvariate(
            math.log(self.session_length_median),
            self.session_length_sigma)))
        x = rng.randrange(self.grid)
        y = rng.randrange(self.grid)
        level = 0
        vx, vy = rng.choice(_VELOCITIES)
        run_left = rng.randrange(3, 9)
        for step in range(n):
            draw = rng.random()
            b = self.bulk_fraction
            m = b + self.mask_fraction
            p = m + self.pyramid_fraction
            a = p + self.animation_fraction
            if draw < b:
                cls = "bulk"
            elif draw < m:
                cls = "mask"
            elif draw < p:
                cls = "pyramid"
            elif draw < a:
                cls = "animation"
            else:
                cls = "interactive"
            yield Arrival(t=t, session=session, cls=cls, step=step,
                          x=x, y=y, level=level, image=image)
            # Advance the viewport: constant-velocity pan runs with
            # occasional turns (the trajectory shape the PR 10
            # predictor reads), rare zoom level changes.
            if rng.random() < self.zoom_fraction and self.max_level:
                level = min(self.max_level,
                            max(0, level + rng.choice((-1, 1))))
            run_left -= 1
            if run_left <= 0:
                vx, vy = rng.choice(_VELOCITIES)
                run_left = rng.randrange(3, 9)
            x = (x + vx) % self.grid
            y = (y + vy) % self.grid
            t += rng.lognormvariate(
                math.log(self.think_time_median_ms / 1000.0),
                self.think_time_sigma)

    def iter_events(self) -> Iterator[Arrival]:
        """ALL sessions' arrivals merged in time order — lazy: a
        heap-merge over per-session generators, so a 10^6-session
        stream holds one pending arrival per session, never the whole
        tape.  Arrivals past the window (a heavy-tailed session that
        outlives the day) are clipped."""
        streams = (self._session_stream(i) for i in range(self.viewers))
        for arrival in heapq.merge(*streams, key=lambda a: a.t):
            if arrival.t < self.duration_s:
                yield arrival

    def events(self) -> List[Arrival]:
        return list(self.iter_events())

    def natural_rate_tps(self, events: Optional[Sequence[Arrival]] = None
                         ) -> float:
        """The model's own aggregate arrival rate (events per second
        over the window) — what :meth:`schedule` rescales from."""
        evs = self.events() if events is None else events
        if not evs:
            return 0.0
        return len(evs) / self.duration_s

    def schedule(self, offered_tps: float,
                 events: Optional[Sequence[Arrival]] = None
                 ) -> List[Arrival]:
        """The event stream time-compressed to a target offered rate:
        the same session mix, trajectories and relative spacing, with
        every timestamp scaled by ``natural_rate / offered_tps`` — the
        standard open-loop replay sweep (compressing the day, not
        changing the users)."""
        if offered_tps <= 0:
            raise ValueError("offered_tps must be > 0")
        evs = list(self.events() if events is None else events)
        natural = self.natural_rate_tps(evs)
        if natural <= 0:
            return []
        scale = natural / offered_tps
        return [Arrival(t=a.t * scale, session=a.session, cls=a.cls,
                        step=a.step, x=a.x, y=a.y, level=a.level,
                        image=a.image)
                for a in evs]

    def window(self, offered_tps: float, window_s: float,
               events: Optional[Sequence[Arrival]] = None
               ) -> List[Arrival]:
        """A STATIONARY measurement window at a target offered rate.

        :meth:`schedule` rescales the whole day, but the day's edges
        are thin — sessions ramp in after t=0 and drain out before
        t=T, so the first ``window_s`` of a compressed schedule
        carries a fraction of the nominal rate (measured: 0.45x asked
        came out 0.1x).  The capacity sweep instead samples the
        STREAM'S STEADY STATE: the central slice between the 30th and
        70th percentile event times (widened when a high rate needs
        more events), re-zeroed and rescaled so the slice's own rate
        equals ``offered_tps``, cut at ``window_s``.  Raises when the
        model simply has too few events for the asked window —
        silently under-offering would corrupt the knee."""
        if offered_tps <= 0 or window_s <= 0:
            raise ValueError("offered_tps and window_s must be > 0")
        evs = list(self.events() if events is None else events)
        needed = int(math.ceil(offered_tps * window_s))
        if len(evs) < needed:
            raise ValueError(
                f"load model has {len(evs)} events but the window "
                f"needs {needed}: raise viewers (or duration)")
        n = len(evs)
        frac = 0.2
        while True:
            lo_i = int((0.5 - frac) * n)
            hi_i = max(lo_i + 2, int((0.5 + frac) * n))
            mid = evs[lo_i:min(hi_i, n)]
            if len(mid) >= needed or frac >= 0.5:
                break
            frac = min(0.5, frac * 1.5)
        # Exactly ``needed`` events rescaled so the last lands at the
        # window edge: the in-window average rate is then the target
        # BY CONSTRUCTION (a slice-average rescale under-offers when
        # the slice's local density varies), while the heavy-tailed
        # relative spacing — the arrival bunching the knee feels — is
        # preserved.
        take = mid[:needed]
        t_lo = take[0].t
        if needed < 2:
            return [Arrival(t=0.0, session=take[0].session,
                            cls=take[0].cls, step=take[0].step,
                            x=take[0].x, y=take[0].y,
                            level=take[0].level,
                            image=take[0].image)]
        scale = window_s / max(take[-1].t - t_lo, 1e-9)
        return [Arrival(t=(a.t - t_lo) * scale, session=a.session,
                        cls=a.cls, step=a.step, x=a.x, y=a.y,
                        level=a.level, image=a.image)
                for a in take]


# ------------------------------------------------------------- execution

@dataclass
class LoadReport:
    """One load leg's outcome: per-class latencies, sheds, errors and
    schedule slip.  ``late_ms`` is the worst behind-schedule fire —
    the open-loop integrity number (a generator that cannot keep its
    own schedule is measuring itself, not the service)."""

    offered_tps: float = 0.0
    window_s: float = 0.0
    latencies_ms: Dict[str, List[float]] = field(default_factory=dict)
    served: int = 0
    sheds: int = 0
    errors: List[str] = field(default_factory=list)
    late_fires: int = 0
    late_ms: float = 0.0

    def all_latencies(self) -> List[float]:
        out: List[float] = []
        for vals in self.latencies_ms.values():
            out.extend(vals)
        return out

    def p99_ms(self) -> Optional[float]:
        vals = sorted(self.all_latencies())
        if not vals:
            return None
        return vals[int(0.99 * (len(vals) - 1))]

    def shed_rate(self) -> float:
        total = self.served + self.sheds
        return self.sheds / total if total else 0.0

    def as_point(self) -> dict:
        return {
            "offered_tps": round(self.offered_tps, 1),
            "p99_ms": (round(self.p99_ms(), 1)
                       if self.p99_ms() is not None else None),
            "shed_rate": round(self.shed_rate(), 4),
            "served": self.served,
            "sheds": self.sheds,
            "late_ms": round(self.late_ms, 1),
        }


# A fire more than this far behind schedule counts as late (scheduler
# jitter under it is noise, not an integrity problem).
_LATE_TOLERANCE_S = 0.025


async def _one(submit, arrival: Arrival, report: LoadReport,
               shed_types: tuple) -> None:
    t0 = time.perf_counter()
    try:
        await submit(arrival)
    except shed_types:
        report.sheds += 1
        telemetry.LOADMODEL.count_shed()
        return
    except Exception as e:     # noqa: BLE001 — the drill's gate input
        report.errors.append(repr(e)[:200])
        return
    report.latencies_ms.setdefault(arrival.cls, []).append(
        (time.perf_counter() - t0) * 1000.0)
    report.served += 1
    telemetry.LOADMODEL.count_completed(arrival.cls)


def _shed_types() -> tuple:
    from ..server.errors import OverloadedError
    return (OverloadedError,)


async def run_open_loop(submit: Callable[[Arrival], Awaitable],
                        arrivals: Iterable[Arrival],
                        offered_tps: float = 0.0,
                        stop: Optional[asyncio.Event] = None
                        ) -> LoadReport:
    """Fire each arrival on schedule REGARDLESS of completions.

    ``submit`` is the service seam (an async callable raising
    ``OverloadedError`` on a shed); every arrival becomes its own
    task at its scheduled offset from the window start — a slow
    service changes nothing about when the next arrival fires, which
    is the entire point.  ``stop`` (optional) aborts the remaining
    schedule early (the elasticity drill's phase boundary)."""
    shed_types = _shed_types()
    report = LoadReport(offered_tps=offered_tps)
    loop = asyncio.get_running_loop()
    t0 = loop.time()
    tasks: List[asyncio.Task] = []
    last_t = 0.0
    for arrival in arrivals:
        if stop is not None and stop.is_set():
            break
        delay = arrival.t - (loop.time() - t0)
        if delay > 0:
            await asyncio.sleep(delay)
        elif -delay > _LATE_TOLERANCE_S:
            report.late_fires += 1
            report.late_ms = max(report.late_ms, -delay * 1000.0)
            telemetry.LOADMODEL.count_late()
        telemetry.LOADMODEL.count_offered(arrival.cls)
        tasks.append(loop.create_task(
            _one(submit, arrival, report, shed_types)))
        last_t = arrival.t
    if tasks:
        await asyncio.gather(*tasks)
    report.window_s = max(last_t, loop.time() - t0, 1e-6)
    return report


async def run_closed_loop(submit: Callable[[Arrival], Awaitable],
                          arrivals: Sequence[Arrival],
                          concurrency: int = 8) -> LoadReport:
    """The SAME arrival list, closed-loop: a fixed worker pool pulls
    the next arrival only after its previous one COMPLETED.  The
    schedule timestamps are ignored by construction — that is the
    flattering lie this leg exists to demonstrate: past the capacity
    knee the workers self-throttle to exactly the service rate, so
    queues never build and the reported p99 stays near the service
    time while the open-loop p99 (same offered load) collapses."""
    shed_types = _shed_types()
    report = LoadReport(
        offered_tps=(len(arrivals) / max(arrivals[-1].t, 1e-6)
                     if arrivals else 0.0))
    queue: "asyncio.Queue[Arrival]" = asyncio.Queue()
    for arrival in arrivals:
        queue.put_nowait(arrival)

    async def worker() -> None:
        while True:
            try:
                arrival = queue.get_nowait()
            except asyncio.QueueEmpty:
                return
            telemetry.LOADMODEL.count_offered(arrival.cls)
            await _one(submit, arrival, report, shed_types)

    t0 = asyncio.get_running_loop().time()
    await asyncio.gather(*(worker()
                           for _ in range(max(1, concurrency))))
    report.window_s = max(asyncio.get_running_loop().time() - t0, 1e-6)
    return report


# ------------------------------------------------------------ knee math

def find_knee(points: Sequence[dict], slo_ms: float,
              max_shed_rate: float = 0.05
              ) -> Tuple[Optional[float], Optional[float], bool]:
    """The capacity knee of one fleet size's measured curve.

    ``points`` is an offered-load-ascending list of
    ``{offered_tps, p99_ms, shed_rate}``; the knee is the HIGHEST
    offered load whose p99 still meets the SLO and whose shed rate
    stays under ``max_shed_rate``.  Returns ``(knee_tps,
    p99_at_knee_ms, censored)`` — ``censored`` means every measured
    point passed, so the true knee lies past the sweep (the curve
    must be re-run wider before the number is trusted); a first point
    that already violates returns ``(None, None, False)`` (the knee
    lies below the sweep — equally loud)."""
    knee = None
    p99_at_knee = None
    violated = False
    for point in points:
        p99 = point.get("p99_ms")
        shed = point.get("shed_rate", 0.0)
        ok = (p99 is not None and p99 <= slo_ms
              and shed <= max_shed_rate)
        if ok and not violated:
            knee = float(point["offered_tps"])
            p99_at_knee = float(p99)
        elif not ok:
            violated = True
    return knee, p99_at_knee, not violated


# ----------------------------------------------------- diurnal estimate

class DiurnalEstimator:
    """Diurnal-phase demand estimate fitted from OBSERVED arrivals.

    The autoscaler's demand signal (PR 13) was flat: viewport-tracked
    sessions x a steady per-session rate — blind to WHERE in the day
    the fleet sits, so a scale decision at the morning ramp provisions
    for the quiet minute it was measured in.  This estimator closes
    that follow-on: :meth:`observe` bins arrival timestamps (O(1) per
    request, bounded ring of bins), and :meth:`fit` runs a single-tone
    harmonic regression

        ``rate(t) ~= a + b*sin(w t) + c*cos(w t)``,  ``w = 2*pi/T``

    over the held bins — the closed-form least squares of the model's
    own half-sine day (``LoadModel`` intensity ``1 + A sin(pi t/T)``
    is exactly one half-period of a tone with period ``2T``, so the
    fit recovers the generator's amplitude/phase; property-tested in
    tests/test_loadmodel.py).  :meth:`multiplier` then answers
    ``rate(now + horizon) / mean_rate`` clamped to a sane band — the
    factor the autoscaler multiplies its session-demand estimate by,
    so shrink decisions inside a rising ramp see the demand the
    shrink completes INTO.

    Deliberately conservative: unfit (too few bins, too little time
    span, or a fitted amplitude within noise) multiplies by exactly
    1.0 — the estimator can only ever ADD phase awareness, never
    subtract the flat signal's floor.
    """

    #: Clamp band for the multiplier: a fit can at most quarter or
    #: quadruple the flat demand estimate (a wild fit from a sparse
    #: tape must not park the fleet or slam it to the ceiling).
    MIN_MULT, MAX_MULT = 0.25, 4.0

    def __init__(self, period_s: float = 86400.0,
                 bin_s: Optional[float] = None,
                 min_bins: int = 8,
                 min_span_fraction: float = 0.25,
                 clock: Callable[[], float] = time.time):
        if period_s <= 0:
            raise ValueError("diurnal period_s must be > 0")
        self.period_s = float(period_s)
        self.bin_s = float(bin_s) if bin_s else self.period_s / 48.0
        if self.bin_s <= 0:
            raise ValueError("diurnal bin_s must be > 0")
        # Hold up to two periods of bins: enough span for a stable
        # tone fit, bounded forever.
        self.max_bins = max(int(min_bins),
                            int(2 * self.period_s / self.bin_s) + 1)
        self.min_bins = int(min_bins)
        self.min_span_fraction = float(min_span_fraction)
        self.clock = clock
        # bin index -> count; insertion-ordered so eviction drops the
        # oldest observation window first.
        self._bins: "Dict[int, int]" = {}
        self._fit: Optional[Tuple[float, float, float]] = None
        self._fit_at_bin: Optional[int] = None

    # ------------------------------------------------------- observation

    def observe(self, t: Optional[float] = None) -> None:
        """Record one arrival (ns-scale: one dict bump)."""
        t = self.clock() if t is None else float(t)
        b = int(t // self.bin_s)
        if b in self._bins:
            self._bins[b] += 1
            return
        self._bins[b] = 1
        while len(self._bins) > self.max_bins:
            self._bins.pop(next(iter(self._bins)))

    # -------------------------------------------------------------- fit

    def fit(self) -> Optional[Tuple[float, float, float]]:
        """(a, b, c) of the harmonic regression over CLOSED bins (the
        live bin is still filling — including it would read every
        fresh bin as a demand cliff), or None when the tape is too
        short.  Closed form via the 3x3 normal equations — no numpy,
        this module stays import-light."""
        now_bin = int(self.clock() // self.bin_s)
        observed = [(b, n) for b, n in self._bins.items()
                    if b < now_bin]
        if len(observed) < self.min_bins:
            return None
        # The regression must see the TROUGH too: a bin inside the
        # observed span that received no arrivals is a true zero-rate
        # point, not a missing one — leaving it out regresses only
        # over the busy phase and systematically flattens the fitted
        # amplitude (the overnight blind spot this estimator exists
        # to close).  Zero-filled across [oldest, newest] observed
        # closed bins, bounded to the ring's own two periods.
        last = min(max(b for b, _ in observed) + 1, now_bin)
        first = max(min(b for b, _ in observed),
                    last - self.max_bins)
        closed = [(b, self._bins.get(b, 0))
                  for b in range(first, last)]
        span = len(closed) * self.bin_s
        if span < self.min_span_fraction * self.period_s:
            return None          # a flat sliver fits anything
        w = 2.0 * math.pi / self.period_s
        # Normal equations for y ~ a + b sin + c cos.
        s = [[0.0] * 3 for _ in range(3)]
        v = [0.0, 0.0, 0.0]
        for b, n in closed:
            t = (b + 0.5) * self.bin_s
            row = (1.0, math.sin(w * t), math.cos(w * t))
            y = n / self.bin_s          # rate, not count
            for i in range(3):
                v[i] += row[i] * y
                for j in range(3):
                    s[i][j] += row[i] * row[j]
        # Gaussian elimination with partial pivoting (3x3).
        m = [s[i] + [v[i]] for i in range(3)]
        for col in range(3):
            piv = max(range(col, 3), key=lambda r: abs(m[r][col]))
            if abs(m[piv][col]) < 1e-12:
                return None             # degenerate design (all bins
                # at one phase): no tone is identifiable
            m[col], m[piv] = m[piv], m[col]
            for r in range(3):
                if r == col:
                    continue
                f = m[r][col] / m[col][col]
                for c in range(col, 4):
                    m[r][c] -= f * m[col][c]
        a, bb, cc = (m[i][3] / m[i][i] for i in range(3))
        if a <= 0:
            return None
        self._fit = (a, bb, cc)
        self._fit_at_bin = now_bin
        return self._fit

    @property
    def amplitude(self) -> Optional[float]:
        """Fitted relative amplitude sqrt(b^2+c^2)/a — comparable to
        the LoadModel's ``diurnal_amplitude`` on a matching period."""
        if self._fit is None:
            return None
        a, b, c = self._fit
        return math.hypot(b, c) / a

    @property
    def phase_s(self) -> Optional[float]:
        """Fitted phase offset in seconds: where the tone's upward
        zero-crossing sits relative to t=0 of the clock."""
        if self._fit is None:
            return None
        a, b, c = self._fit
        w = 2.0 * math.pi / self.period_s
        return math.atan2(c, b) / w

    # -------------------------------------------------------- prediction

    def multiplier(self, at: Optional[float] = None,
                   horizon_s: float = 0.0) -> float:
        """``rate(at + horizon) / mean_rate`` under the current fit,
        clamped to [MIN_MULT, MAX_MULT]; exactly 1.0 while unfit.  The
        fit is refreshed lazily at most once per closed bin."""
        now_bin = int(self.clock() // self.bin_s)
        if self._fit is None or self._fit_at_bin != now_bin:
            self.fit()
        if self._fit is None:
            return 1.0
        a, b, c = self._fit
        t = (self.clock() if at is None else float(at)) \
            + float(horizon_s)
        w = 2.0 * math.pi / self.period_s
        rate = a + b * math.sin(w * t) + c * math.cos(w * t)
        if rate <= 0:
            return self.MIN_MULT
        return max(self.MIN_MULT, min(self.MAX_MULT, rate / a))

    def reset(self) -> None:
        self._bins.clear()
        self._fit = None
        self._fit_at_bin = None
