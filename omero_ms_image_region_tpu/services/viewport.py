"""Per-session viewport model: pan/zoom trajectories -> predictions.

The reference exists to serve interactive OMERO.web viewers (PAPER.md
L5): a user PANS (tile requests march along a lattice direction) and
ZOOMS (requests jump resolution levels around one viewport center).
``services.prefetch`` used to guess blindly — the four lattice
neighbors of every served tile, no notion of who is asking or where
they are headed.  This module gives the prefetcher a client model:

* :class:`ViewportTracker` holds a bounded LRU of per-session states
  (sessions resolved from the existing request ctx —
  ``ctx.omero_session_key``; sessionless traffic shares the anonymous
  state), each a short deque of recent tile observations.
* :meth:`ViewportTracker.predict` turns a session's recent trajectory
  into an ordered list of PREDICTED next tiles: the velocity estimate
  (median per-step tile delta over the recent window) extrapolated
  ``lookahead`` steps ahead, plus next-zoom tiles when the last
  observation changed resolution levels (a zoom in flight predicts the
  same viewport center at the level the client is heading to).
* No trajectory (first touch, or a teleport) falls back to the classic
  4-neighbor lattice guess — strictly better-informed, never less.

Deterministic by construction: the clock is injectable and nothing
here samples randomness, so tests and ``bench.py --smoke --sessions``
replay identical traces to identical predictions.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Deque, List, Optional, Tuple

from ..utils import telemetry

# Observations older than this never vote in the velocity estimate —
# a viewer that paused for a coffee did not keep panning.
_STALE_S = 10.0


@dataclass(frozen=True)
class TilePrediction:
    """One predicted future tile request of a session (same z/t/image
    as the observation stream; ``resolution`` may differ on zooms)."""

    image_id: int
    z: int
    t: int
    resolution: Optional[int]
    x: int
    y: int
    # Ordering hint: step 1 = most imminent.  Prefetchers schedule in
    # ascending step order so the budget spends on the near future.
    step: int = 1


class _Obs:
    __slots__ = ("ts", "image_id", "z", "t", "resolution", "x", "y")

    def __init__(self, ts, image_id, z, t, resolution, x, y):
        self.ts = ts
        self.image_id = image_id
        self.z = z
        self.t = t
        self.resolution = resolution
        self.x = x
        self.y = y


class _SessionState:
    __slots__ = ("history",)

    def __init__(self, maxlen: int):
        self.history: Deque[_Obs] = deque(maxlen=maxlen)


def _median_int(values: List[int]) -> int:
    """Deterministic integer median (lower of the middle pair)."""
    ordered = sorted(values)
    return ordered[(len(ordered) - 1) // 2]


class ViewportTracker:
    """Bounded LRU of per-session pan/zoom trajectories.

    Thread-safe (observations arrive from asyncio worker threads via
    the handler's read path); the per-session history is tiny and the
    lock is held for dict/deque ops only.
    """

    ANONYMOUS = ""

    def __init__(self, max_sessions: int = 4096, history: int = 8,
                 clock: Callable[[], float] = time.monotonic):
        if max_sessions < 1:
            raise ValueError("viewport max_sessions must be >= 1")
        if history < 2:
            raise ValueError("viewport history must be >= 2")
        self.max_sessions = max_sessions
        self.history = history
        self.clock = clock
        self._lock = threading.Lock()
        self._sessions: "OrderedDict[str, _SessionState]" = OrderedDict()
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._sessions)

    @staticmethod
    def _key(session_key: Optional[str]) -> str:
        return session_key if session_key else ViewportTracker.ANONYMOUS

    def _touch(self, key: str) -> _SessionState:
        """Get-or-create the session's state at the LRU head, with
        eviction + gauge bookkeeping.  Caller holds the lock."""
        state = self._sessions.get(key)
        if state is None:
            state = _SessionState(self.history)
            self._sessions[key] = state
            while len(self._sessions) > self.max_sessions:
                self._sessions.popitem(last=False)
                self.evictions += 1
                telemetry.SESSIONS.count_evicted()
        else:
            self._sessions.move_to_end(key)
        telemetry.SESSIONS.set_tracked(len(self._sessions))
        return state

    def observe(self, session_key: Optional[str], image_id: int,
                z: int, t: int, resolution: Optional[int],
                x: int, y: int) -> None:
        """Record one served tile request for the session."""
        key = self._key(session_key)
        now = self.clock()
        with self._lock:
            state = self._touch(key)
            state.history.append(
                _Obs(now, image_id, z, t, resolution, x, y))
            # Counted here, not in _touch: observations_total is the
            # VIEWPORT-lattice feed (what the predictor reads) — mask
            # activity keeps the session live but never counts as one.
            telemetry.SESSIONS.count_observation()

    def observe_activity(self, session_key: Optional[str]) -> None:
        """Record NON-TILE session activity (shape-mask requests):
        keeps the session live in the LRU and counted in the tracked
        gauge — the demand figure the autoscaler reads — without
        polluting the pan/zoom trajectory (a mask request has no
        lattice coordinates to vote with)."""
        with self._lock:
            self._touch(self._key(session_key))

    # ------------------------------------------------------- prediction

    def _recent(self, session_key: Optional[str]) -> List[_Obs]:
        with self._lock:
            state = self._sessions.get(self._key(session_key))
            if state is None:
                return []
            return list(state.history)

    def velocity(self, session_key: Optional[str]
                 ) -> Optional[Tuple[int, int]]:
        """The session's per-step tile velocity ``(vx, vy)`` on its
        current image/plane/level — the median of consecutive deltas
        over the fresh history — or None when there is no same-level
        trajectory to read."""
        history = self._recent(session_key)
        if len(history) < 2:
            return None
        last = history[-1]
        now = self.clock()
        dxs: List[int] = []
        dys: List[int] = []
        for prev, cur in zip(history, history[1:]):
            if (cur.image_id != last.image_id
                    or prev.image_id != last.image_id
                    or cur.resolution != last.resolution
                    or prev.resolution != last.resolution
                    or cur.z != last.z or cur.t != last.t
                    or now - cur.ts > _STALE_S
                    # The gap WITHIN the pair matters too: the single
                    # resume delta after a pause spans the teleport
                    # (e.g. 35 tiles "per step") and must not be the
                    # one fresh vote that defines the velocity.
                    or cur.ts - prev.ts > _STALE_S):
                continue
            dxs.append(cur.x - prev.x)
            dys.append(cur.y - prev.y)
        if not dxs:
            return None
        return _median_int(dxs), _median_int(dys)

    def scrub_velocity(self, session_key: Optional[str]
                       ) -> Optional[Tuple[int, int]]:
        """The session's per-step plane velocity ``(dz, dt)`` — the
        median of consecutive z/t deltas over the fresh history while
        the viewport itself holds still (same image/level/tile) — or
        None when no scrub trajectory is in flight.  This is the focus/
        time SCRUB a viewer drives with the z/t sliders: the lattice
        velocity estimate deliberately excludes those pairs (z/t change
        disqualifies a pan vote), so without this reader a scrubbing
        session looks stationary to the prefetcher."""
        history = self._recent(session_key)
        if len(history) < 2:
            return None
        last = history[-1]
        now = self.clock()
        dzs: List[int] = []
        dts: List[int] = []
        for prev, cur in zip(history, history[1:]):
            if (cur.image_id != last.image_id
                    or prev.image_id != last.image_id
                    or cur.resolution != last.resolution
                    or prev.resolution != last.resolution
                    or cur.x != prev.x or cur.y != prev.y
                    or (cur.z == prev.z and cur.t == prev.t)
                    or now - cur.ts > _STALE_S
                    or cur.ts - prev.ts > _STALE_S):
                continue
            dzs.append(cur.z - prev.z)
            dts.append(cur.t - prev.t)
        if not dzs:
            return None
        return _median_int(dzs), _median_int(dts)

    def zoom_direction(self, session_key: Optional[str]) -> int:
        """-1 zooming IN (toward finer levels — resolution indexes are
        largest-first, so the index DECREASES), +1 zooming out, 0 no
        zoom in flight."""
        history = self._recent(session_key)
        if len(history) < 2:
            return 0
        prev, last = history[-2], history[-1]
        if (prev.image_id != last.image_id
                or prev.resolution is None or last.resolution is None
                or prev.resolution == last.resolution):
            return 0
        return 1 if last.resolution > prev.resolution else -1

    def predict(self, session_key: Optional[str],
                lookahead: int = 2,
                max_level: Optional[int] = None
                ) -> List[TilePrediction]:
        """Predicted next tiles for the session, most imminent first.

        * Pan in flight: extrapolate the velocity ``lookahead`` steps.
        * z/t scrub in flight: the same tile on the planes the slider
          is heading to, ``lookahead`` steps of the median z/t delta.
        * Zoom in flight: the last tile's center re-expressed at the
          next level in the zoom direction (children when zooming in,
          the parent when zooming out).
        * Neither: empty (the prefetcher falls back to the lattice
          neighbors of the served tile).

        Coordinates may run past the plane edge — the prefetcher clamps
        through the same region pipeline as the foreground read, which
        discards out-of-plane tiles.
        """
        history = self._recent(session_key)
        if not history:
            return []
        last = history[-1]
        out: List[TilePrediction] = []
        vel = self.velocity(session_key)
        if vel is not None and vel != (0, 0):
            vx, vy = vel
            for i in range(1, max(1, lookahead) + 1):
                nx, ny = last.x + vx * i, last.y + vy * i
                if nx < 0 or ny < 0:
                    break
                out.append(TilePrediction(
                    last.image_id, last.z, last.t, last.resolution,
                    nx, ny, step=i))
        scrub = self.scrub_velocity(session_key)
        if scrub is not None and scrub != (0, 0):
            # z/t scrub in flight: the same tile at the planes the
            # slider is heading to (sliders clamp at the stack edge,
            # so negative targets are simply not predicted).
            dz, dt = scrub
            for i in range(1, max(1, lookahead) + 1):
                nz, nt = last.z + dz * i, last.t + dt * i
                if nz < 0 or nt < 0:
                    break
                out.append(TilePrediction(
                    last.image_id, nz, nt, last.resolution,
                    last.x, last.y, step=i))
        zoom = self.zoom_direction(session_key)
        if zoom != 0 and last.resolution is not None:
            target = last.resolution + zoom
            if target >= 0 and (max_level is None
                                or target <= max_level):
                if zoom < 0:
                    # Finer level: the tile's four children cover the
                    # same viewport region at 2x the lattice density.
                    for j, (cx, cy) in enumerate((
                            (2 * last.x, 2 * last.y),
                            (2 * last.x + 1, 2 * last.y),
                            (2 * last.x, 2 * last.y + 1),
                            (2 * last.x + 1, 2 * last.y + 1))):
                        out.append(TilePrediction(
                            last.image_id, last.z, last.t, target,
                            cx, cy, step=1 + j))
                else:
                    out.append(TilePrediction(
                        last.image_id, last.z, last.t, target,
                        last.x // 2, last.y // 2, step=1))
        return out
