"""Byte caches for rendered regions, masks, and pixels metadata.

Replaces the reference's ``RedisCacheVerticle`` get/set events
(``ImageRegionRequestHandler.java:214-249, 469-477``; ``ShapeMaskVerticle
.java:82-90, 140-148``) and the per-cache enable flags
(``config.yaml:53-60``).

Tiering: a process-local LRU in front of an optional shared Redis, the same
shape as the reference's Hazelcast-memo-in-front-of-Redis layering.  The
local tier prefers the native C++ cache (``native/``) when its shared
library is built, else a pure-Python LRU.  Redis is gated on the ``redis``
package being importable — absent in this image, so deployments without it
still get the local tier.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, List, Optional, Protocol, Tuple

log = logging.getLogger("omero_ms_image_region_tpu.cache")

# Rate limit for tier-failure warnings (one per tier per interval) so an
# outage is visible in logs without flooding them at request rate.
_WARN_INTERVAL_S = 30.0


class CacheTier(Protocol):
    async def get(self, key: str) -> Optional[bytes]: ...
    async def set(self, key: str, value: bytes) -> None: ...


class MemoryLRUCache:
    """Thread-safe size-bounded LRU over bytes values.

    The async face is non-blocking (pure in-memory ops), so ``get``/``set``
    complete synchronously inside the event loop.
    """

    def __init__(self, max_bytes: int = 256 * 1024 * 1024):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._data: "OrderedDict[str, bytes]" = OrderedDict()
        self._size = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get_sync(self, key: str) -> Optional[bytes]:
        with self._lock:
            value = self._data.get(key)
            if value is None:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return value

    def set_sync(self, key: str, value: bytes) -> None:
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self._size -= len(old)
            self._data[key] = value
            self._size += len(value)
            while self._size > self.max_bytes and self._data:
                _, evicted = self._data.popitem(last=False)
                self._size -= len(evicted)
                self.evictions += 1

    def keys_by_recency(self, limit: int = 0) -> List[str]:
        """Resident keys, most-recently-used first — the warm-state
        snapshot's view of the hot set.  ``limit`` 0 = all."""
        with self._lock:
            keys = list(reversed(self._data.keys()))
        return keys[:limit] if limit else keys

    async def contains(self, key: str) -> bool:
        """Residency probe WITHOUT an LRU bump or a hit/miss count —
        the explain plane's dry-run contract (a probe must observe,
        never reorder the working set)."""
        with self._lock:
            return key in self._data

    async def get(self, key: str) -> Optional[bytes]:
        return self.get_sync(key)

    async def set(self, key: str, value: bytes) -> None:
        self.set_sync(key, value)


def _native_cache(max_bytes: int):
    """Native C++ LRU tier if the shared library is available, else None."""
    try:
        from ..native import NativeLRUCache  # noqa: PLC0415
        return NativeLRUCache(max_bytes)
    except Exception:
        return None


class TTLCacheTier(Protocol):
    """A tier that can expire entries (used by the shared canRead memo)."""

    async def set_ttl(self, key: str, value: bytes,
                      ttl_seconds: float) -> None: ...


class RedisCache:
    """Shared Redis byte cache (≙ RedisCacheVerticle). Gated: constructing
    raises ImportError when the ``redis`` package is unavailable."""

    def __init__(self, uri: str):
        import redis.asyncio as aioredis  # noqa: PLC0415
        self._client = aioredis.from_url(uri)

    async def get(self, key: str) -> Optional[bytes]:
        return await self._client.get(key)

    async def set(self, key: str, value: bytes) -> None:
        await self._client.set(key, value)

    async def contains(self, key: str) -> bool:
        """EXISTS probe — no value transfer (explain-plane dry run)."""
        return bool(await self._client.exists(key))

    async def set_ttl(self, key: str, value: bytes,
                      ttl_seconds: float) -> None:
        await self._client.set(key, value, px=max(1, int(ttl_seconds * 1000)))

    async def close(self) -> None:
        await self._client.aclose()


async def get_with_tier(stack, key: str
                        ) -> "Tuple[Optional[bytes], Optional[str]]":
    """``(value, tier_label)`` for any stack-shaped object: a real
    :class:`CacheStack` answers via :meth:`CacheStack.get_tiered`;
    duck-typed test doubles that only implement ``get`` degrade to a
    label-less hit (provenance then reads ``byte_cache``)."""
    fn = getattr(stack, "get_tiered", None)
    if fn is not None:
        return await fn(key)
    return await stack.get(key), None


async def probe_with_tier(stack, key: str) -> Optional[str]:
    """Dry-run twin of :func:`get_with_tier` for the explain plane:
    the holding tier's label (None = not resident), via the stack's
    non-mutating :meth:`CacheStack.probe_tiered` when present; duck-
    typed doubles that only implement ``get`` degrade to a bare get
    labelled "memory"."""
    fn = getattr(stack, "probe_tiered", None)
    if fn is not None:
        return await fn(key)
    return "memory" if (await stack.get(key)) is not None else None


def tier_label(tier) -> str:
    """Short stable label for one cache tier ("memory" / "disk" /
    "redis") — the vocabulary :meth:`CacheStack.get_tiered` reports
    and the explain plane surfaces.  An explicit ``tier_label``
    attribute wins (the namespaced disk views set "disk"); otherwise
    the class name decides, defaulting to "memory" (the native and
    pure-Python LRUs)."""
    explicit = getattr(tier, "tier_label", None)
    if explicit:
        return str(explicit)
    return "redis" if "Redis" in type(tier).__name__ else "memory"


class CacheStack:
    """Read-through tier stack: first hit wins and back-fills upper tiers.

    Tier failures degrade, never fail the request: a broken tier (e.g. a
    Redis outage) reads as a miss and writes are dropped — the render path
    must keep serving uncached rather than turning every request into a
    500 (the reference likewise treats cache errors as misses, replying to
    the Redis-get event with null on failure).
    """

    def __init__(self, tiers: List[CacheTier], enabled: bool = True):
        self.tiers = tiers
        self.enabled = enabled
        self._last_warn: Dict[int, float] = {}

    def _warn_tier(self, i: int, op: str, e: Exception) -> None:
        now = time.monotonic()
        if now - self._last_warn.get(i, 0.0) >= _WARN_INTERVAL_S:
            self._last_warn[i] = now
            log.warning("cache tier %d (%s) %s failed, degrading: %s",
                        i, type(self.tiers[i]).__name__, op, e)

    async def get(self, key: str) -> Optional[bytes]:
        value, _tier = await self.get_tiered(key)
        return value

    async def probe_tiered(self, key: str) -> Optional[str]:
        """DRY-RUN residency probe: the first tier holding ``key``
        (its label), with NO back-fill, NO LRU bump and no value
        fetch where the tier supports a ``contains`` check — the
        explain plane must observe the caches, never promote cold
        payloads into the memory tier or reorder the working set.
        Tiers without ``contains`` degrade to a bare ``get`` (still
        no back-fill)."""
        if not self.enabled:
            return None
        for i, tier in enumerate(self.tiers):
            try:
                probe = getattr(tier, "contains", None)
                if probe is not None:
                    present = await probe(key)
                else:
                    present = (await tier.get(key)) is not None
            except Exception as e:
                self._warn_tier(i, "probe", e)
                continue
            if present:
                return tier_label(tier)
        return None

    async def get_tiered(self, key: str
                         ) -> "Tuple[Optional[bytes], Optional[str]]":
        """``(value, tier_label)`` — which tier answered ("memory" /
        "disk" / "redis"; None on a miss).  The provenance layer maps
        the label onto its closed byte-source vocabulary; the explain
        plane reports it verbatim.  Same read-through back-fill as
        :meth:`get` (it delegates here)."""
        if not self.enabled:
            return None, None
        for i, tier in enumerate(self.tiers):
            try:
                value = await tier.get(key)
            except Exception as e:
                self._warn_tier(i, "get", e)
                continue
            if value is not None:
                for upper in self.tiers[:i]:
                    try:
                        await upper.set(key, value)
                    except Exception as e:
                        self._warn_tier(self.tiers.index(upper), "set", e)
                return value, tier_label(tier)
        return None, None

    async def set(self, key: str, value: bytes) -> None:
        if not self.enabled:
            return
        results = await asyncio.gather(
            *(t.set(key, value) for t in self.tiers),
            return_exceptions=True)
        for i, r in enumerate(results):
            if isinstance(r, Exception):
                self._warn_tier(i, "set", r)


@dataclass
class CacheConfig:
    """Per-cache enable flags + sizing (≙ ``config.yaml:47-60``).

    Flags default to disabled like the reference's shipped config
    (``config.yaml:53-60``); ``enabled_all`` is the one-liner for tests
    and standalone deployments.
    """

    redis_uri: Optional[str] = None
    local_max_bytes: int = 256 * 1024 * 1024
    # Enable flags, named after the reference's config keys.
    image_region: bool = False         # image-region-cache.enabled
    pixels_metadata: bool = False      # pixels-metadata-cache.enabled
    shape_mask: bool = False           # shape-mask-cache.enabled
    # Durable disk tier (services.diskcache), slotted between the
    # in-memory LRU and Redis so rendered bytes survive process death
    # with no external dependency.  None disables (today's posture);
    # the persistence block (server.config.PersistenceConfig) sets it.
    disk_dir: Optional[str] = None
    disk_max_bytes: int = 1024 * 1024 * 1024
    disk_sync_writes: bool = False     # tests: deterministic writes

    @classmethod
    def enabled_all(cls, **kwargs) -> "CacheConfig":
        return cls(image_region=True, pixels_metadata=True,
                   shape_mask=True, **kwargs)


def make_cache(config: CacheConfig, enabled: bool,
               redis: Optional[RedisCache] = None,
               disk: Optional[CacheTier] = None) -> CacheStack:
    """Build one named cache's tier stack from config.

    ``redis`` is the deployment's one shared client (all stacks ride the
    same connection pool, like the reference's single RedisCacheVerticle);
    ``disk`` is the deployment's one shared durable tier (all stacks
    share its byte budget and write-behind worker), slotted between the
    memory LRU and Redis — a read-through hit there back-fills memory,
    exactly the warm-restart promote path.
    """
    tiers: List[CacheTier] = []
    native = _native_cache(config.local_max_bytes)
    tiers.append(native if native is not None
                 else MemoryLRUCache(config.local_max_bytes))
    if disk is not None:
        tiers.append(disk)
    if redis is not None:
        tiers.append(redis)
    return CacheStack(tiers, enabled=enabled)


class NamespacedTier:
    """Per-cache view of one shared tier: keys gain a namespace prefix
    so the three named caches can share ONE disk store (one byte
    budget, one write-behind worker) without key collisions.  Counter
    attributes delegate, so the generic per-tier /metrics export still
    sees the shared tier's accounting."""

    def __init__(self, inner, prefix: str,
                 tier_label: str = "disk"):
        self.inner = inner
        self.prefix = prefix
        # Provenance/explain vocabulary (services.cache.tier_label):
        # the shared durable tier reads as "disk" wherever it answers.
        self.tier_label = tier_label

    async def get(self, key: str) -> Optional[bytes]:
        return await self.inner.get(self.prefix + key)

    async def set(self, key: str, value: bytes) -> None:
        await self.inner.set(self.prefix + key, value)

    async def contains(self, key: str) -> bool:
        """Dry-run probe (explain plane): delegate a ``contains``
        when the shared tier has one, else fall back to a bare get
        (no back-fill either way — this is a leaf tier)."""
        probe = getattr(self.inner, "contains", None)
        if probe is not None:
            return await probe(self.prefix + key)
        return (await self.inner.get(self.prefix + key)) is not None

    @property
    def hits(self):
        return self.inner.hits

    @property
    def misses(self):
        return self.inner.misses

    @property
    def evictions(self):
        return self.inner.evictions


@dataclass
class Caches:
    """The three named caches the reference runs (``config.yaml:53-60``),
    plus the one shared Redis client they (and the canRead memo) ride
    and the one shared durable disk tier (warm-state persistence)."""

    image_region: CacheStack
    pixels_metadata: CacheStack
    shape_mask: CacheStack
    redis: Optional[RedisCache] = None
    disk: object = None                # services.diskcache.DiskByteCache

    @classmethod
    def from_config(cls, config: CacheConfig) -> "Caches":
        redis = None
        if config.redis_uri:
            try:
                redis = RedisCache(config.redis_uri)
            except ImportError:
                pass
        disk = None
        if config.disk_dir:
            from .diskcache import DiskByteCache
            disk = DiskByteCache(config.disk_dir,
                                 max_bytes=config.disk_max_bytes,
                                 sync_writes=config.disk_sync_writes)

        def disk_view(prefix: str):
            return (NamespacedTier(disk, prefix)
                    if disk is not None else None)

        return cls(
            image_region=make_cache(config, config.image_region, redis,
                                    disk=disk_view("img:")),
            pixels_metadata=make_cache(config, config.pixels_metadata,
                                       redis, disk=disk_view("meta:")),
            shape_mask=make_cache(config, config.shape_mask, redis,
                                  disk=disk_view("mask:")),
            redis=redis,
            disk=disk,
        )

    async def close(self) -> None:
        if self.disk is not None:
            # Drain the write-behind queue so bytes rendered in the
            # last seconds of this life are durable for the next one.
            await asyncio.to_thread(self.disk.close)
        if self.redis is not None:
            await self.redis.close()
