"""OMERO-database-backed metadata + ACL service.

The reference's deployment resolves ``Pixels`` metadata, ``Mask`` shapes
and ``canRead`` decisions from a live OMERO server + PostgreSQL over the
clustered event bus (``ImageRegionRequestHandler.java:316-427``,
``ShapeMaskRequestHandler.java:223-277``).  This module is that backend
implemented directly against the OMERO relational schema: the same
:class:`..services.metadata.MetadataService` protocol as
``LocalMetadataService``, but reading the ``pixels`` / ``shape`` /
``session`` / ``experimentergroup`` tables.

The service is written against a tiny async connection protocol
(:class:`AsyncDb`: ``fetchrow``/``fetch``) so the SQL — the real content —
is engine-portable: production uses asyncpg (gated import; this image does
not ship it), tests run the identical statements through a sqlite adapter
over a seeded OMERO-schema subset (``tests/test_db_metadata.py``).

ACL model (OMERO group permissions): an object row carries
``owner_id``/``group_id``; the *group* row carries the permissions long.
``can_read`` is owner-read for the owner, group-read for members,
world-read otherwise, with members of the ``system`` group (admins)
always allowed — the standard OMERO read semantics the reference's
``omero.can_read`` event resolves.
"""

from __future__ import annotations

import logging
from typing import Any, Mapping, Optional, Protocol, Sequence

from ..models.mask import Mask
from ..models.pixels import Pixels

logger = logging.getLogger(__name__)

# OMERO permissions-long read bits (ome.model.internal.Permissions).
# Derived from the documented canonical values: private 'rw----' = -120
# (0x88), group-read 'rwr---' = -56 (0xC8), read-annotate 'rwra--' = -40
# (0xD8), read-write 'rwrw--' = -8 (0xF8), public-read 'rwr-r-' = -52
# (0xCC).
USER_READ = 0x80
GROUP_READ = 0x40
WORLD_READ = 0x04


class AsyncDb(Protocol):
    """The slice of an asyncpg pool/connection this service consumes."""

    async def fetchrow(self, sql: str, *args: Any
                       ) -> Optional[Mapping[str, Any]]: ...

    async def fetch(self, sql: str, *args: Any
                    ) -> Sequence[Mapping[str, Any]]: ...


_SQL_PIXELS = """
SELECT p.sizex, p.sizey, p.sizez, p.sizec, p.sizet, pt.value AS pixels_type
FROM pixels p JOIN pixelstype pt ON p.pixelstype = pt.id
WHERE p.image = $1
"""

_SQL_IMAGE_ACL = """
SELECT i.owner_id, i.group_id, g.permissions
FROM image i JOIN experimentergroup g ON i.group_id = g.id
WHERE i.id = $1
"""

_SQL_SHAPE_ACL = """
SELECT s.owner_id, s.group_id, g.permissions
FROM shape s JOIN experimentergroup g ON s.group_id = g.id
WHERE s.id = $1
"""

_SQL_SESSION_USER = """
SELECT s.owner FROM session s WHERE s.uuid = $1 AND s.closed IS NULL
"""

_SQL_IS_MEMBER = """
SELECT 1 AS yes FROM groupexperimentermap m WHERE m.child = $1 AND m.parent = $2
"""

_SQL_IS_ADMIN = """
SELECT 1 AS yes FROM groupexperimentermap m
JOIN experimentergroup g ON m.parent = g.id
WHERE m.child = $1 AND g.name = 'system'
"""

_SQL_MASK = """
SELECT s.width, s.height, s.bytes, s.fillcolor
FROM shape s WHERE s.id = $1 AND s.bytes IS NOT NULL
"""

# Binary-repository resolution (the file-path resolver bean +
# Bio-Formats behind PixelsService.getPixelBuffer,
# beanRefContext.xml:13-21, ImageRegionRequestHandler.java:302-309):
# an OMERO 5 import lands in the ManagedRepository as the fileset's
# originalfile rows (path is repo-relative, name the filename).
_SQL_FILESET_FILES = """
SELECT f.path AS path, f.name AS name
FROM image i
JOIN filesetentry fe ON fe.fileset = i.fileset
JOIN originalfile f ON fe.originalfile = f.id
WHERE i.id = $1
ORDER BY fe.id
"""

# Pre-FS images have no fileset; their pixel data is the legacy ROMIO
# file <omero.data.dir>/Pixels/<pixels_id> (the "/OMERO/Pixels" bean).
_SQL_PIXELS_ID = """
SELECT p.id AS id FROM pixels p WHERE p.image = $1
"""


def _romio_rel_path(pixels_id: int) -> str:
    """Legacy ROMIO path for a pixels id, with the Dir-### fan-out
    (``ome.io.nio.AbstractFileSystemService``): ids >= 1000 nest into
    3-digit-group subdirectories — 1234 lives at ``Pixels/Dir-001/1234``,
    1234567 at ``Pixels/Dir-001/Dir-234/1234567``."""
    suffix = ""
    remaining = pixels_id
    while remaining > 999:
        remaining //= 1000
        if remaining > 0:
            suffix = f"/Dir-{remaining % 1000:03d}" + suffix
    return f"Pixels{suffix}/{pixels_id}"


def _unpack_fillcolor(value: Optional[int]):
    """OMERO stores shape colors as one signed 32-bit RGBA int."""
    if value is None:
        return None
    v = value & 0xFFFFFFFF
    return ((v >> 24) & 0xFF, (v >> 16) & 0xFF, (v >> 8) & 0xFF, v & 0xFF)


class DbMetadataService:
    """`MetadataService` over an OMERO-schema database connection."""

    def __init__(self, db: AsyncDb):
        self.db = db

    # ------------------------------------------------------------ pixels

    async def get_pixels_description(self, image_id: int,
                                     session_key: Optional[str]
                                     ) -> Optional[Pixels]:
        if not await self.can_read("Image", image_id, session_key):
            return None
        row = await self.db.fetchrow(_SQL_PIXELS, image_id)
        if row is None:
            return None
        return Pixels(
            image_id=image_id,
            pixels_type=row["pixels_type"],
            size_x=int(row["sizex"]),
            size_y=int(row["sizey"]),
            size_z=int(row["sizez"]),
            size_c=int(row["sizec"]),
            size_t=int(row["sizet"]),
        )

    # ------------------------------------------------------ binary repo

    async def resolve_image_paths(self, image_id: int) -> list:
        """Repo-root-relative candidate paths for an image's pixel data.

        OMERO 5 filesets resolve to their ManagedRepository files;
        pre-FS images fall back to the legacy ``Pixels/<pixels_id>``
        ROMIO file.  No ACL here — callers resolve paths only after
        ``can_read`` has already gated the request (the reference's
        resolver bean is likewise permission-blind).
        """
        out = []
        for row in await self.db.fetch(_SQL_FILESET_FILES, image_id):
            path = (row["path"] or "").strip("/")
            name = (row["name"] or "").strip("/")
            if not name:
                continue
            rel = f"{path}/{name}" if path else name
            out.append(f"ManagedRepository/{rel}")
        if not out:
            row = await self.db.fetchrow(_SQL_PIXELS_ID, image_id)
            if row is not None:
                out.append(_romio_rel_path(int(row["id"])))
        return out

    # --------------------------------------------------------------- ACL

    async def _session_user(self, session_key: Optional[str]
                            ) -> Optional[int]:
        if session_key is None:
            return None
        row = await self.db.fetchrow(_SQL_SESSION_USER, session_key)
        return None if row is None else int(row["owner"])

    async def can_read(self, object_type: str, object_id: int,
                       session_key: Optional[str]) -> bool:
        sql = _SQL_IMAGE_ACL if object_type == "Image" else _SQL_SHAPE_ACL
        row = await self.db.fetchrow(sql, object_id)
        if row is None:
            return False
        perms = int(row["permissions"])
        user = await self._session_user(session_key)
        if user is None:
            # Anonymous: only world-readable groups serve.
            return bool(perms & WORLD_READ)
        if user == int(row["owner_id"]):
            return bool(perms & USER_READ)
        if await self.db.fetchrow(_SQL_IS_ADMIN, user) is not None:
            return True
        if await self.db.fetchrow(
                _SQL_IS_MEMBER, user, int(row["group_id"])) is not None:
            return bool(perms & GROUP_READ)
        return bool(perms & WORLD_READ)

    # -------------------------------------------------------------- mask

    async def get_mask(self, shape_id: int,
                       session_key: Optional[str]) -> Optional[Mask]:
        if not await self.can_read("Mask", shape_id, session_key):
            return None
        row = await self.db.fetchrow(_SQL_MASK, shape_id)
        if row is None:
            return None
        return Mask(
            shape_id=shape_id,
            width=int(row["width"]),
            height=int(row["height"]),
            bytes_=bytes(row["bytes"]),
            fill_color=_unpack_fillcolor(row["fillcolor"]),
        )


class PostgresMetadataService(DbMetadataService):
    """asyncpg-backed production wiring (gated: asyncpg is optional).

    Use :meth:`connect` to build one from a DSN; raises ImportError when
    asyncpg is unavailable so callers can degrade the way the session
    stores do (``server/app.py::_make_session_store``).
    """

    def __init__(self, pool):
        super().__init__(pool)
        self._pool = pool

    @classmethod
    async def connect(cls, dsn: str, min_size: int = 1,
                      max_size: int = 4) -> "PostgresMetadataService":
        import asyncpg  # ImportError here = caller falls back

        pool = await asyncpg.create_pool(dsn, min_size=min_size,
                                         max_size=max_size)
        return cls(pool)

    async def close(self) -> None:
        await self._pool.close()
