"""Predictive, budgeted tile prefetch into the HBM raw cache.

SURVEY.md §2b maps the reference's ``PixelBuffer`` surface to "a tile
reader service with host-pinned staging -> HBM, async prefetch"; this is
the prefetch half — now SESSION-AWARE.  Each served tile feeds the
per-session viewport model (:mod:`services.viewport`), and what gets
speculatively staged is that session's PREDICTED next tiles (velocity
extrapolation, next-zoom children/parent) instead of a blind lattice
guess; sessions with no trajectory yet fall back to the classic four
lattice neighbors.

Three contracts this layer holds:

* **Budgeted, never binary.**  ``max_pending`` is scaled continuously:
  by this prefetcher's own ``budget_scale`` and by the pressure
  governor's :meth:`~..server.pressure.PressureGovernor.prefetch_budget`
  (elevated pressure halves the budget, critical quarters it, the
  ``pause_prefetch`` ladder step floors it at 0).  Budget changes take
  effect on QUEUED work too: a pool item that starts after the budget
  hit zero exits without reading a byte — ``flush()`` during a pause no
  longer waits out loads nobody wants (the PR 9 pause/flush bug).
* **Fleet-aware.**  With ``cache_for_route`` installed (the combined
  fleet wires ``FleetRouter.cache_for_route``), every predicted tile
  stages into the HBM shard of the member that will SERVE it — routed
  by the same ``plane_route_key`` the router hashes — so prefetch warms
  the right shard and never duplicates a plane across members (the
  digest-deduped staging path is unchanged underneath).
* **Accountable.**  Staged keys are remembered (bounded) and the
  handler reports foreground hits back through :meth:`note_hit`, so the
  predictive hit rate is a measured number (``imageregion_prefetch_*``,
  ``bench.py --smoke --sessions``), not a hope.

Prefetch stays strictly best-effort: failures are swallowed (the
foreground path re-reads on demand), and nothing is scheduled when the
region is not tile-shaped (full-plane and arbitrary-region requests
don't pan).
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
from collections import OrderedDict
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..io.devicecache import DeviceRawCache, region_key
from ..utils import telemetry

logger = logging.getLogger(__name__)

# Staged-key memory bound: enough to cover every plane the HBM tiers
# can hold, small enough to never matter.
_STAGED_KEYS_MAX = 8192


class _RouteStub:
    """The minimal ctx shape ``parallel.fleet.plane_route_key`` hashes:
    a predicted tile's SOURCE-PLANE identity, built exactly the way the
    future foreground request will build it — so the prefetch route and
    the serve route can never disagree."""

    __slots__ = ("image_id", "z", "t", "resolution", "tile", "region")

    def __init__(self, image_id, z, t, resolution, tile):
        self.image_id = image_id
        self.z = z
        self.t = t
        self.resolution = resolution
        self.tile = tile
        self.region = None


class TilePrefetcher:
    """Stages predicted next tiles of each session into the device
    cache tier that will serve them."""

    def __init__(self, raw_cache: DeviceRawCache, max_workers: int = 2,
                 max_pending: int = 16, viewport=None,
                 cache_for_route: Optional[Callable] = None,
                 lookahead: int = 2):
        self.raw_cache = raw_cache
        self.max_pending = max_pending
        # services.viewport.ViewportTracker (None = lattice-only).
        self.viewport = viewport
        # Fleet seam: route_key -> the owning member's DeviceRawCache
        # (None return = stage locally).  Installed by create_app for
        # combined fleets; absent everywhere else.
        self.cache_for_route = cache_for_route
        # Cross-host seam (parallel.federation): when the predicted
        # plane's ring owner is a REMOTE member,
        # ``remote_prestage(route, entry) -> bool`` hints the owner's
        # host to stage it from ITS pixel store (fire-and-forget wire
        # op) — speculation warms the member that will serve the
        # request, never this host's wrong shard.  Installed by
        # create_app for federated fleets; absent everywhere else.
        self.remote_prestage = None
        # Hot-key seam (``FleetRouter.local_replica_caches``): a
        # promoted route is read-balanced across an R>1 replica set,
        # so its predictions must warm EVERY local replica shard — a
        # balanced read landing on a cold replica re-reads from disk
        # and the promotion buys nothing.  Empty/None for unpromoted
        # routes and non-fleet deployments.
        self.replica_caches: Optional[Callable] = None
        self.lookahead = max(1, int(lookahead))
        # Local budget scale in [0, 1]; multiplied with the pressure
        # governor's prefetch_budget().  The brownout ladder's
        # ``pause_prefetch`` actuator drives this through the ``paused``
        # property (budget 0 — the binary flag is now the budget floor).
        self.budget_scale = 1.0
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="tile-prefetch")
        self._lock = threading.Lock()
        self._pending: set = set()
        self._futures: set = set()
        # Keys this prefetcher staged, awaiting their foreground hit.
        self._staged_keys: "OrderedDict" = OrderedDict()
        self.scheduled = 0
        self.staged = 0
        self.hits = 0
        self.predicted = 0

    # ------------------------------------------------------------ budget

    @property
    def paused(self) -> bool:
        """Binary view of the budget floor (kept for the PR 9 ladder
        actuator and its tests): paused == budget 0."""
        return self.budget_scale <= 0.0

    @paused.setter
    def paused(self, value: bool) -> None:
        self.budget_scale = 0.0 if value else 1.0

    def effective_budget(self) -> float:
        """This instant's combined budget scale: local x governor."""
        scale = self.budget_scale
        if scale <= 0.0:
            return 0.0
        from ..server.pressure import active
        governor = active()
        if governor is not None:
            scale *= governor.prefetch_budget()
        return max(0.0, min(1.0, scale))

    def effective_max_pending(self) -> int:
        """The pending-slot bound this instant (0 = fully paused)."""
        return int(self.max_pending * self.effective_budget())

    # ------------------------------------------------------- accounting

    def _mark_staged(self, key) -> None:
        with self._lock:
            self._staged_keys[key] = True
            while len(self._staged_keys) > _STAGED_KEYS_MAX:
                self._staged_keys.popitem(last=False)

    def note_hit(self, key) -> None:
        """The foreground path found ``key`` resident: if this
        prefetcher staged it, that is a PREDICTIVE HIT — the pan/zoom
        step paid render + encode only."""
        with self._lock:
            if self._staged_keys.pop(key, None) is None:
                return
            self.hits += 1
        telemetry.PREFETCH.count_hit()

    def hit_rate(self) -> Optional[float]:
        """Predictive hit rate: staged planes the foreground came back
        for, over planes staged.  None before anything staged."""
        if self.staged == 0:
            return None
        return self.hits / self.staged

    # ------------------------------------------------------- candidates

    def _candidates(self, ctx_like: Tuple, session_key: Optional[str],
                    tile) -> List[Tuple[Optional[int], int, int, int,
                                        int]]:
        """Predicted (resolution, z, t, x, y) tuples for this serve —
        the session's viewport predictions when a trajectory exists,
        else the four lattice neighbors of the served tile."""
        image_id, z, t, resolution = ctx_like
        out: List[Tuple[Optional[int], int, int, int, int]] = []
        if self.viewport is not None:
            predictions = self.viewport.predict(
                session_key, lookahead=self.lookahead)
            for p in predictions:
                if p.image_id != image_id:
                    continue
                out.append((p.resolution, p.z, p.t, p.x, p.y))
            if out:
                self.predicted += len(out)
                telemetry.PREFETCH.count_predicted(len(out))
                telemetry.FLIGHT.record(
                    "prefetch.predict", n=len(out),
                    session=(session_key or "-")[:16],
                    x=tile.x, y=tile.y)
                return out
        for dx, dy in ((-1, 0), (1, 0), (0, -1), (0, 1)):
            nx, ny = tile.x + dx, tile.y + dy
            if nx < 0 or ny < 0:
                continue
            out.append((resolution, z, t, nx, ny))
        return out

    # --------------------------------------------------------- schedule

    def tile_served(self, src, image_id: int, z: int, t: int,
                    resolution, levels, tile, tile_size,
                    max_tile_length: int, active: Sequence[int],
                    flip_horizontal: bool = False,
                    flip_vertical: bool = False,
                    session_key: Optional[str] = None) -> None:
        """Feed the viewport model and schedule the session's predicted
        tiles.

        Candidate regions resolve through the same ``get_region_def`` /
        ``clamp_region_to_plane`` pipeline (flips included) as the
        foreground read, so the cache keys are guaranteed identical to
        the ones the next pan/zoom request will compute.
        """
        from ..server.region import (RegionDef, clamp_region_to_plane,
                                     get_region_def)

        if tile is None:
            return
        if self.viewport is not None:
            self.viewport.observe(session_key, image_id, z, t,
                                  resolution, tile.x, tile.y)
        budget = self.effective_max_pending()
        if budget <= 0:
            telemetry.PREFETCH.count_skipped("budget")
            return
        for (nres, nz, nt, nx, ny) in self._candidates(
                (image_id, z, t, resolution), session_key, tile):
            if nres is not None and not 0 <= nres < len(levels):
                continue
            ntile = RegionDef(x=nx, y=ny, width=tile.width,
                              height=tile.height)
            region = get_region_def(levels, nres, ntile, None,
                                    tile_size, max_tile_length,
                                    flip_horizontal, flip_vertical)
            clamp_region_to_plane(levels, nres, region)
            if region.width <= 0 or region.height <= 0:
                continue
            level = nres or 0
            key = region_key(image_id, nz, nt, level,
                             region.as_tuple(), tuple(active))
            # Fleet routing: the predicted tile stages into the HBM
            # shard of the member that will serve it (route computed
            # from the REQUEST identity, exactly like the router).
            from ..parallel.fleet import plane_route_key
            route = plane_route_key(_RouteStub(image_id, nz, nt, nres,
                                               ntile))
            cache = self.raw_cache
            if self.cache_for_route is not None:
                routed = self.cache_for_route(route)
                if routed is not None:
                    cache = routed
                elif self.remote_prestage is not None:
                    # No local cache owns this route: its ring owner
                    # lives on another host — hand IT the prediction
                    # (a prestage hint; the owner reads the region
                    # from its own store through the digest-deduped
                    # staging path) and spend nothing here.
                    entry = {"key": [image_id, nz, nt, level,
                                     list(region.as_tuple()),
                                     list(active)],
                             "route": route}
                    if self.remote_prestage(route, entry):
                        self.predicted += 1
                        continue
            # Hot-route replication: when the router promoted this
            # route, stage the prediction into every LOCAL replica
            # shard, not just the routed owner's.
            targets = [cache]
            if self.replica_caches is not None:
                try:
                    reps = list(self.replica_caches(route) or ())
                except Exception:
                    reps = []
                targets += [c for c in reps if c is not cache]
            for tcache in targets:
                # Replica stagings carry a per-cache token so two
                # shards can hold the same key in flight at once.
                token = key if tcache is cache else (id(tcache), key)
                if tcache is None or key in tcache:
                    continue   # already resident: no pool churn
                with self._lock:
                    if token in self._pending:
                        # Already in flight: dedupe, not a budget
                        # signal — counting it as one would read as
                        # exhaustion on dashboards while slots sit
                        # free.
                        continue
                    if len(self._pending) >= budget:
                        telemetry.PREFETCH.count_skipped("budget")
                        continue
                    self._pending.add(token)
                try:
                    future = self._pool.submit(
                        self._load, src, tcache, key, route, nz, nt,
                        level, region, active, token)
                except RuntimeError:   # pool shut down mid-request
                    with self._lock:
                        self._pending.discard(token)
                    return
                self.scheduled += 1
                telemetry.PREFETCH.count_scheduled()
                with self._lock:
                    self._futures.add(future)
                future.add_done_callback(
                    lambda f: self._futures.discard(f))

    def _load(self, src, cache, key, route, z: int, t: int, level: int,
              region, active: Sequence[int], token=None) -> None:
        if token is None:
            token = key
        try:
            # Budget changes bind QUEUED work too: an item whose turn
            # comes after the budget hit zero exits without touching
            # the store — pausing mid-flight cancels the backlog's
            # effect, and flush() during a pause settles immediately.
            if self.effective_budget() <= 0.0:
                telemetry.PREFETCH.count_skipped("paused")
                return

            loaded = [False]

            def loader() -> np.ndarray:
                loaded[0] = True
                planes = [src.get_region(z, c, t, region, level)
                          for c in active]
                return np.stack(planes)

            cache.get_or_load(key, loader, route_key=route)
            if loaded[0]:
                self.staged += 1
                telemetry.PREFETCH.count_staged()
                self._mark_staged(key)
        except Exception as e:  # best-effort: foreground re-reads on miss
            logger.debug("prefetch failed for %s: %r", key, e)
        finally:
            with self._lock:
                self._pending.discard(token)

    def flush(self, timeout: float = 10.0) -> None:
        """Wait for in-flight prefetches (tests/shutdown).  Paused
        (budget-0) backlogs settle immediately — queued items exit at
        the budget check instead of loading."""
        with self._lock:
            outstanding = list(self._futures)
        concurrent.futures.wait(outstanding, timeout=timeout)

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
