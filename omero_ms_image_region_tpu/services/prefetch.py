"""Pan-ahead tile prefetch into the HBM raw cache.

SURVEY.md §2b maps the reference's ``PixelBuffer`` surface to "a tile
reader service with host-pinned staging -> HBM, async prefetch"; this is
the prefetch half.  Deep-zoom clients pan in steps of one tile, so after
serving a tile the four lattice neighbors (same z/t/level/channels) are
read and staged to device in background threads — the next pan step finds
its raw planes already resident and pays only render + encode.

Prefetch is strictly best-effort: failures are swallowed (the foreground
path re-reads on demand), and nothing is scheduled when the region is not
tile-shaped (full-plane and arbitrary-region requests don't pan).
"""

from __future__ import annotations

import concurrent.futures
import logging
import threading
from typing import Sequence

import numpy as np

from ..io.devicecache import DeviceRawCache, region_key

logger = logging.getLogger(__name__)


class TilePrefetcher:
    """Stages neighbor tiles of each served tile into the device cache."""

    def __init__(self, raw_cache: DeviceRawCache, max_workers: int = 2,
                 max_pending: int = 16):
        self.raw_cache = raw_cache
        self.max_pending = max_pending
        # Brownout ladder hook (server.pressure "pause_prefetch"): a
        # paused prefetcher schedules nothing — speculative staging is
        # the first work to go when HBM or the link is drowning.  The
        # foreground path is untouched (it re-reads on demand).
        self.paused = False
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="tile-prefetch")
        self._lock = threading.Lock()
        self._pending: set = set()
        self._futures: set = set()
        self.scheduled = 0

    def tile_served(self, src, image_id: int, z: int, t: int,
                    resolution, levels, tile, tile_size,
                    max_tile_length: int, active: Sequence[int],
                    flip_horizontal: bool = False,
                    flip_vertical: bool = False) -> None:
        """Schedule the four lattice neighbors of the served tile.

        Neighbor regions resolve through the same ``get_region_def`` /
        ``clamp_region_to_plane`` pipeline (flips included) as the
        foreground read, so the cache keys are guaranteed identical to
        the ones the next pan request will compute.
        """
        from ..server.region import (RegionDef, clamp_region_to_plane,
                                     get_region_def)

        if tile is None or self.paused:
            return
        level = resolution or 0
        for dx, dy in ((-1, 0), (1, 0), (0, -1), (0, 1)):
            ntile = RegionDef(x=tile.x + dx, y=tile.y + dy,
                              width=tile.width, height=tile.height)
            if ntile.x < 0 or ntile.y < 0:
                continue
            region = get_region_def(levels, resolution, ntile, None,
                                    tile_size, max_tile_length,
                                    flip_horizontal, flip_vertical)
            clamp_region_to_plane(levels, resolution, region)
            if region.width <= 0 or region.height <= 0:
                continue
            key = region_key(image_id, z, t, level, region.as_tuple(),
                             tuple(active))
            if key in self.raw_cache:
                continue   # already resident: no pool churn
            with self._lock:
                if key in self._pending or len(
                        self._pending) >= self.max_pending:
                    continue
                self._pending.add(key)
            try:
                future = self._pool.submit(self._load, src, key, z, t,
                                           level, region, active)
            except RuntimeError:   # pool shut down mid-request
                with self._lock:
                    self._pending.discard(key)
                return
            self.scheduled += 1
            with self._lock:
                self._futures.add(future)
            future.add_done_callback(
                lambda f: self._futures.discard(f))

    def _load(self, src, key, z: int, t: int, level: int, region,
              active: Sequence[int]) -> None:
        try:
            def loader() -> np.ndarray:
                planes = [src.get_region(z, c, t, region, level)
                          for c in active]
                return np.stack(planes)

            self.raw_cache.get_or_load(key, loader)
        except Exception as e:  # best-effort: foreground re-reads on miss
            logger.debug("prefetch failed for %s: %r", key, e)
        finally:
            with self._lock:
                self._pending.discard(key)

    def flush(self, timeout: float = 10.0) -> None:
        """Wait for in-flight prefetches (tests/shutdown)."""
        with self._lock:
            outstanding = list(self._futures)
        concurrent.futures.wait(outstanding, timeout=timeout)

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
