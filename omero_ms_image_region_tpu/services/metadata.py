"""Metadata + ACL service (≙ the OMERO backbone event-bus services).

The reference fetches ``Pixels`` metadata, ``Mask`` objects, and read-ACL
decisions from the OMERO server JVM over the clustered event bus
(addresses ``omero.get_pixels_description`` / ``omero.get_object`` /
``omero.can_read``; ``ImageRegionRequestHandler.java:80-84, 316-427``,
``ShapeMaskRequestHandler.java:223-277``).  Here the same three calls are an
async protocol with a local filesystem-backed implementation; a remote
(gRPC/DB) implementation can slot in without touching the handlers.

ACL model: each image/mask directory may carry an ``acl.json`` —
``{"public": true}`` or ``{"sessions": ["key", ...]}``.  Absent file =
public (the standalone dev posture).  ``CanReadMemo`` mirrors the
Hazelcast distributed ``canRead`` memo map keyed by
``(session, type, id)`` (``ImageRegionVerticle.java:59-60, 107-111``).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, Optional, Protocol, Tuple

from ..models.mask import Mask
from ..models.pixels import Pixels

logger = logging.getLogger(__name__)


class MetadataService(Protocol):
    async def get_pixels_description(self, image_id: int,
                                     session_key: Optional[str]
                                     ) -> Optional[Pixels]: ...

    async def can_read(self, object_type: str, object_id: int,
                       session_key: Optional[str]) -> bool: ...

    async def get_mask(self, shape_id: int,
                       session_key: Optional[str]) -> Optional[Mask]: ...


# Level-0 dataset path per NGFF root, validated by the root .zattrs
# mtime: the path only changes when .zattrs changes, so the per-request
# freshness stamp stays stat-only (the JSON parse runs once per
# rewrite, not once per tile).
_NGFF_LEVEL0: Dict[str, Tuple[int, Optional[str]]] = {}
_NGFF_LEVEL0_LOCK = threading.Lock()


def _ngff_level0_zarray(ngff: str, zattrs_mtime_ns: int
                        ) -> Optional[str]:
    with _NGFF_LEVEL0_LOCK:
        cached = _NGFF_LEVEL0.get(ngff)
        if cached is not None and cached[0] == zattrs_mtime_ns:
            return cached[1]
    path = None
    try:
        with open(os.path.join(ngff, ".zattrs")) as f:
            attrs = json.load(f)
        datasets = (attrs.get("multiscales") or [{}])[0] \
            .get("datasets") or []
        if datasets and datasets[0].get("path"):
            path = os.path.join(ngff, datasets[0]["path"], ".zarray")
    except (OSError, ValueError, KeyError, IndexError):
        pass    # malformed/absent .zattrs: the parse downstream complains
    with _NGFF_LEVEL0_LOCK:
        _NGFF_LEVEL0[ngff] = (zattrs_mtime_ns, path)
    return path


def _ngff_meta_mtime(ngff: str) -> int:
    """Freshness stamp for an NGFF group's geometry.

    Stats the metadata FILES, not the directory (an in-place rewrite
    replaces contents without touching the directory mtime) — and
    includes the first multiscales level's ``.zarray``: the per-level
    files carry the shapes, so rewriting level 0 in place without
    touching the root ``.zattrs`` must still invalidate cached Pixels
    geometry."""
    candidates = [os.path.join(ngff, ".zattrs"),
                  os.path.join(ngff, ".zarray")]
    try:
        zattrs_mtime = os.stat(candidates[0]).st_mtime_ns
    except OSError:
        zattrs_mtime = 0
    if zattrs_mtime:
        level0 = _ngff_level0_zarray(ngff, zattrs_mtime)
        if level0 is not None:
            candidates.append(level0)
    return max((os.stat(p).st_mtime_ns for p in candidates
                if os.path.exists(p)),
               default=os.stat(ngff).st_mtime_ns)


def _check_acl(path: str, session_key: Optional[str]) -> bool:
    acl_file = os.path.join(path, "acl.json")
    if not os.path.exists(acl_file):
        return True
    with open(acl_file) as f:
        acl = json.load(f)
    if acl.get("public"):
        return True
    return session_key is not None and session_key in acl.get("sessions", [])


class LocalMetadataService:
    """Filesystem-backed metadata: ``<data_dir>/<image_id>/meta.json`` for
    pixels, ``<data_dir>/masks/<shape_id>.json`` (+ ``.bin`` packed bits)
    for masks."""

    # Source-mtime memo TTL: Last-Modified headers tolerate seconds of
    # staleness (HTTP-dates have second precision anyway), and the memo
    # keeps the per-request cost to one dict hit instead of a listdir.
    _MTIME_TTL_S = 5.0

    def __init__(self, data_dir: str):
        self.data_dir = data_dir
        # (path, mtime_ns)-validated Pixels memo for TIFF-backed images
        # (the chunked path's meta.json read is cheap enough bare).
        self._tiff_pixels: Dict[int, tuple] = {}
        # image_id -> (expires_monotonic, mtime-or-None) memo for
        # source_mtime (the Last-Modified path).
        self._mtime_memo: Dict[int, Tuple[float, Optional[float]]] = {}
        self._mtime_lock = threading.Lock()

    def _image_dir(self, image_id: int) -> str:
        return os.path.join(self.data_dir, str(image_id))

    def _mask_base(self, shape_id: int) -> str:
        return os.path.join(self.data_dir, "masks", str(shape_id))

    async def get_pixels_description(self, image_id: int,
                                     session_key: Optional[str]
                                     ) -> Optional[Pixels]:
        meta_path = os.path.join(self._image_dir(image_id), "meta.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                m = json.load(f)
            return Pixels(
                image_id=image_id,
                pixels_type=m.get("pixels_type", m["dtype"]),
                size_x=m["levels"][0]["size_x"],
                size_y=m["levels"][0]["size_y"],
                size_z=m["size_z"],
                size_c=m["size_c"],
                size_t=m["size_t"],
            )
        # OME-NGFF-backed image: geometry from the zarr/multiscales
        # JSON.  Same discipline as the TIFF branch below: the listdir
        # + per-level JSON parses run off the event loop and cache per
        # (path, mtime) — a WSI pyramid re-parses only when rewritten.
        import asyncio

        from ..io.ngff import find_ngff
        ngff = await asyncio.to_thread(
            find_ngff, self._image_dir(image_id))
        if ngff is not None:
            # File IO (two stats + a small JSON read) runs off the
            # event loop like the parse below.
            mtime = await asyncio.to_thread(_ngff_meta_mtime, ngff)
            cached = self._tiff_pixels.get(image_id)
            if cached is not None and cached[0] == (ngff, mtime):
                return cached[1]
            px = await asyncio.to_thread(self._parse_ngff_pixels,
                                         image_id, ngff)
            self._tiff_pixels[image_id] = ((ngff, mtime), px)
            return px
        # OME-TIFF-backed image: geometry from the OME-XML / IFDs (the
        # reference resolves the same fields from the OMERO DB, which
        # Bio-Formats populated at import; here the file is the truth).
        # The parse walks every IFD, so cache per (path, mtime) and run
        # it off the event loop; repeat requests additionally hit the
        # handler's metadata write-back cache upstream.
        import asyncio

        from ..io.ometiff import find_tiff
        tiff = find_tiff(self._image_dir(image_id))
        if tiff is None:
            return None
        mtime = os.stat(tiff).st_mtime_ns
        cached = self._tiff_pixels.get(image_id)
        if cached is not None and cached[0] == (tiff, mtime):
            return cached[1]
        px = await asyncio.to_thread(self._parse_tiff_pixels,
                                     image_id, tiff)
        self._tiff_pixels[image_id] = ((tiff, mtime), px)
        return px

    def _parse_ngff_pixels(self, image_id: int, ngff: str) -> Pixels:
        import numpy as np

        from ..io.ngff import NgffZarrSource
        src = NgffZarrSource(ngff)
        return Pixels(
            image_id=image_id,
            pixels_type=np.dtype(src.dtype).name,
            size_x=src.size_x, size_y=src.size_y,
            size_z=src.size_z, size_c=src.size_c,
            size_t=src.size_t,
        )

    def _parse_tiff_pixels(self, image_id: int, tiff: str) -> Pixels:
        from ..io.ometiff import OmeTiffSource
        src = OmeTiffSource(tiff)
        try:
            (size_x, size_y) = src.resolution_descriptions()[0]
            return Pixels(
                image_id=image_id,
                pixels_type=src.pixels_type,
                size_x=size_x,
                size_y=size_y,
                size_z=src.size_z,
                size_c=src.size_c,
                size_t=src.size_t,
            )
        finally:
            src.close()

    def source_mtime_cached(self, image_id: int
                            ) -> Tuple[bool, Optional[float]]:
        """Memo peek: ``(hit, mtime)`` without any filesystem work —
        the hot path's inline fast path (a thread-pool hop per
        request just to reach a dict hit would cost more than the
        lookup; only a memo MISS pays the off-loop stat walk)."""
        now = time.monotonic()
        with self._mtime_lock:
            hit = self._mtime_memo.get(image_id)
            if hit is not None and hit[0] > now:
                return True, hit[1]
        return False, None

    def source_mtime(self, image_id: int) -> Optional[float]:
        """The image's ingest/source mtime (unix seconds) — the
        Last-Modified stamp for conditional HTTP.  Newest of the
        metadata files an ingest touches (meta.json, the NGFF group's
        geometry stamp, the TIFF itself) and the image directory;
        None when the image does not exist.  Memoized for a few
        seconds (``_MTIME_TTL_S``) so the hot path pays a dict hit,
        not a listdir, per request."""
        now = time.monotonic()
        with self._mtime_lock:
            hit = self._mtime_memo.get(image_id)
            if hit is not None and hit[0] > now:
                return hit[1]
        mtime: Optional[float] = None
        image_dir = self._image_dir(image_id)
        candidates = []
        try:
            candidates.append(os.stat(image_dir).st_mtime)
            meta = os.path.join(image_dir, "meta.json")
            if os.path.exists(meta):
                candidates.append(os.stat(meta).st_mtime)
            from ..io.ngff import find_ngff
            ngff = find_ngff(image_dir)
            if ngff is not None:
                candidates.append(_ngff_meta_mtime(ngff) / 1e9)
            else:
                from ..io.ometiff import find_tiff
                tiff = find_tiff(image_dir)
                if tiff is not None:
                    candidates.append(os.stat(tiff).st_mtime)
        except OSError:
            pass
        if candidates:
            mtime = max(candidates)
        with self._mtime_lock:
            self._mtime_memo[image_id] = (now + self._MTIME_TTL_S,
                                          mtime)
            if len(self._mtime_memo) > 4096:    # bounded, coarse
                self._mtime_memo.clear()
        return mtime

    async def can_read(self, object_type: str, object_id: int,
                       session_key: Optional[str]) -> bool:
        if object_type == "Image":
            path = self._image_dir(object_id)
        else:
            path = self._mask_base(object_id)
            # Mask ACLs live next to the mask json as <id>.acl.json.
            acl = path + ".acl.json"
            if os.path.exists(acl):
                with open(acl) as f:
                    a = json.load(f)
                if a.get("public"):
                    return True
                return (session_key is not None
                        and session_key in a.get("sessions", []))
            return os.path.exists(path + ".json")
        if not os.path.exists(path):
            return False
        return _check_acl(path, session_key)

    async def get_mask(self, shape_id: int,
                       session_key: Optional[str]) -> Optional[Mask]:
        base = self._mask_base(shape_id)
        if not os.path.exists(base + ".json"):
            return None
        with open(base + ".json") as f:
            m = json.load(f)
        with open(base + ".bin", "rb") as f:
            bits = f.read()
        fill = m.get("fill_color")
        return Mask(
            shape_id=shape_id,
            width=m["width"],
            height=m["height"],
            bytes_=bits,
            fill_color=None if fill is None else tuple(fill),
        )


def write_mask(data_dir: str, mask: Mask) -> None:
    """Persist a mask in the layout ``LocalMetadataService`` reads."""
    os.makedirs(os.path.join(data_dir, "masks"), exist_ok=True)
    base = os.path.join(data_dir, "masks", str(mask.shape_id))
    with open(base + ".json", "w") as f:
        json.dump({
            "width": mask.width,
            "height": mask.height,
            "fill_color": (None if mask.fill_color is None
                           else list(mask.fill_color)),
        }, f)
    with open(base + ".bin", "wb") as f:
        f.write(mask.bytes_)


class CanReadMemo:
    """TTL memo of ACL decisions keyed by (session, type, id)
    (≙ the Hazelcast ``canRead`` map the workers share,
    ``ImageRegionVerticle.java:107-111``).

    Two tiers: an in-process TTL dict, plus an optional ``shared`` cache
    tier (Redis in a multi-instance deployment) that plays the Hazelcast
    distributed-map role — a decision memoized by one service instance is
    visible to the rest.  The shared tier stores b"1"/b"0" with this
    memo's TTL when it supports expiry (``set_ttl``); a tier without
    expiry support is written through plain ``set`` and should only be
    used where staleness is acceptable (ACL revocations would otherwise
    never be re-checked).
    """

    def __init__(self, ttl_seconds: float = 60.0, shared=None):
        self.ttl = ttl_seconds
        self.shared = shared
        self._lock = threading.Lock()
        self._memo: Dict[Tuple[Optional[str], str, int],
                         Tuple[bool, float]] = {}

    @staticmethod
    def _shared_key(session_key: Optional[str], object_type: str,
                    object_id: int) -> str:
        return f"canRead:{session_key or ''}:{object_type}:{object_id}"

    async def get_async(self, session_key: Optional[str], object_type: str,
                        object_id: int) -> Optional[bool]:
        local = self.get(session_key, object_type, object_id)
        if local is not None or self.shared is None:
            return local
        # A shared-tier failure is a miss, never a request failure (same
        # degradation policy as CacheStack): the ACL service itself still
        # answers.
        try:
            raw = await self.shared.get(
                self._shared_key(session_key, object_type, object_id))
        except Exception as e:
            logger.warning("shared canRead memo get failed: %r", e)
            return None
        if raw is None:
            return None
        value = raw == b"1"
        self.put(session_key, object_type, object_id, value)
        return value

    async def put_async(self, session_key: Optional[str], object_type: str,
                        object_id: int, value: bool) -> None:
        self.put(session_key, object_type, object_id, value)
        if self.shared is not None:
            key = self._shared_key(session_key, object_type, object_id)
            payload = b"1" if value else b"0"
            try:
                set_ttl = getattr(self.shared, "set_ttl", None)
                if set_ttl is not None:
                    await set_ttl(key, payload, self.ttl)
                else:
                    await self.shared.set(key, payload)
            except Exception as e:
                logger.warning("shared canRead memo set failed: %r", e)

    def get(self, session_key: Optional[str], object_type: str,
            object_id: int) -> Optional[bool]:
        key = (session_key, object_type, object_id)
        with self._lock:
            hit = self._memo.get(key)
            if hit is None:
                return None
            value, expires = hit
            if time.monotonic() > expires:
                del self._memo[key]
                return None
            return value

    def put(self, session_key: Optional[str], object_type: str,
            object_id: int, value: bool) -> None:
        with self._lock:
            self._memo[(session_key, object_type, object_id)] = (
                value, time.monotonic() + self.ttl,
            )
