"""Baseline JFIF entropy coder over device-produced JPEG coefficients.

Pure-Python reference implementation of the serial half of baseline JPEG:
per-image optimal Huffman table construction (ITU T.81 Annex K.2), DC
prediction, AC run-length coding, bit packing with 0xFF byte stuffing, and
JFIF/DQT/SOF0/DHT/SOS framing.  The native fast path
(``native/jpegenc.cpp``) implements the identical deterministic algorithm;
tests assert byte-for-byte equality between the two.

Input contract (from :mod:`.ops.jpegenc`): zigzagged int16 coefficient
blocks in raster order for one image — ``y[(H16*2)*(W16*2), 64]``,
``cb[H16*W16, 64]``, ``cr[H16*W16, 64]`` where ``H16 = ceil(H/16)`` —
assembled here into 4:2:0 interleaved MCUs (per T.81 A.2.3 the Y blocks of
an MCU scan 2x2 left-to-right, top-to-bottom, then Cb, then Cr).

The reference microservice's JPEG stage is CPU-side ``LocalCompress``
(``ImageRegionRequestHandler.java:457-460,580-582``); this module plus the
device DCT kernel replace it end to end.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import numpy as np

from .ops.jpegenc import quant_tables, zigzag_order


# ------------------------------------------------------- huffman (K.2)

def _code_sizes(freq: np.ndarray) -> np.ndarray:
    """T.81 K.2 figure K.1: code length per symbol from frequencies.

    ``freq`` has 257 entries; index 256 is the reserved pseudo-symbol with
    frequency 1 guaranteeing no real symbol gets the all-ones code.
    """
    freq = freq.astype(np.int64).copy()
    codesize = np.zeros(257, dtype=np.int32)
    others = np.full(257, -1, dtype=np.int32)
    while True:
        nz = np.nonzero(freq > 0)[0]
        if len(nz) < 2:
            break
        # v1 = least-frequency symbol, ties -> largest symbol value.
        f = freq[nz]
        v1 = nz[np.flatnonzero(f == f.min())[-1]]
        rest = nz[nz != v1]
        f2 = freq[rest]
        v2 = rest[np.flatnonzero(f2 == f2.min())[-1]]
        freq[v1] += freq[v2]
        freq[v2] = 0
        codesize[v1] += 1
        while others[v1] != -1:
            v1 = others[v1]
            codesize[v1] += 1
        others[v1] = v2
        codesize[v2] += 1
        while others[v2] != -1:
            v2 = others[v2]
            codesize[v2] += 1
    return codesize


def _limit_to_16(bits: np.ndarray) -> np.ndarray:
    """T.81 K.2 figure K.3 ADJUST_BITS: cap code lengths at 16."""
    bits = bits.copy()
    i = len(bits) - 1
    while i > 16:
        if bits[i] > 0:
            j = i - 2
            while bits[j] == 0:
                j -= 1
            bits[i] -= 2
            bits[i - 1] += 1
            bits[j + 1] += 2
            bits[j] -= 1
        else:
            i -= 1
    # Remove the reserved pseudo-symbol's code (largest value, so it owns
    # the longest code; K.2 figure K.3 final step).
    i = 16
    while bits[i] == 0:
        i -= 1
    bits[i] -= 1
    return bits


def build_huffman_table(freq256: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Optimal baseline Huffman table -> (BITS[1..16], HUFFVAL).

    Returns ``bits`` i32[17] (index 0 unused) and the symbol list ordered
    by (code length, symbol value) — the canonical DHT payload.
    """
    freq = np.zeros(257, dtype=np.int64)
    freq[:256] = freq256
    freq[256] = 1
    codesize = _code_sizes(freq)
    bits = np.zeros(33, dtype=np.int32)
    for size in codesize[codesize > 0]:
        bits[size] += 1
    bits = _limit_to_16(bits)[:17]
    order = np.argsort(codesize[:256] * 256 + np.arange(256), kind="stable")
    huffval = np.array(
        [s for s in order if codesize[s] > 0], dtype=np.uint8
    )
    return bits, huffval


@functools.lru_cache(maxsize=1)
def fixed_huffman_spec():
    """Deterministic shared Huffman tables for one-pass (device) encoding.

    Optimal per-image tables need a frequency pass; the device bit-packer
    runs one pass with these fixed tables instead (a few percent larger
    streams).  Built from a smoothed synthetic frequency profile — small
    runs and small magnitudes dominate — with every legal symbol given a
    nonzero count so every symbol has a code.  One DC and one AC table
    serve all three components.

    Returns ``(dc_bits, dc_vals, dc_code, dc_len, ac_bits, ac_vals,
    ac_code, ac_len)`` where the code/len arrays are indexed by symbol.
    """
    dc_freq = np.zeros(256, dtype=np.int64)
    for s in range(12):
        dc_freq[s] = 1 + (1 << max(0, 14 - 2 * s))
    ac_freq = np.zeros(256, dtype=np.int64)
    for run in range(16):
        for size in range(1, 11):
            ac_freq[(run << 4) | size] = 1 + (1 << max(0, 18 - run - 2 * size))
    ac_freq[0x00] = 1 << 17   # EOB
    ac_freq[0xF0] = 1 << 8    # ZRL
    dc_bits, dc_vals = build_huffman_table(dc_freq)
    ac_bits, ac_vals = build_huffman_table(ac_freq)
    dc_code, dc_len = _codes_from_table(dc_bits, dc_vals)
    ac_code, ac_len = _codes_from_table(ac_bits, ac_vals)
    return (dc_bits, dc_vals, dc_code, dc_len,
            ac_bits, ac_vals, ac_code, ac_len)


def tuned_huffman_spec(dc_freq: np.ndarray, ac_freq: np.ndarray):
    """Huffman spec tuned to MEASURED symbol frequencies, in the same
    8-tuple shape as :func:`fixed_huffman_spec`.

    Unlike a per-image optimal table (which may omit symbols), these
    tables serve FUTURE content of the same workload, so every legal
    symbol keeps a code: add-1 smoothing over the full legal alphabet
    (DC categories 0..11; AC (run,size) with size 1..10, plus EOB and
    ZRL) — unseen symbols land at the long-code end, seen symbols get
    frequency-proportional short codes.  Typical gain on WSI-class
    content: ~4-8% smaller streams than the fixed profile, which is
    wire time AND payload on every tile.
    """
    # Measured counts scale by 256 so the +1 keep-alive pseudo-counts
    # stay negligible even for small samples (a plain add-1 over the
    # 174-symbol alphabet would flatten a few-KB sample's distribution
    # toward uniform and LOSE to the fixed profile).
    dc = np.zeros(256, dtype=np.int64)
    for s in range(12):
        dc[s] = 1 + (int(dc_freq[s]) << 8)
    ac = np.zeros(256, dtype=np.int64)
    for run in range(16):
        for size in range(1, 11):
            sym = (run << 4) | size
            ac[sym] = 1 + (int(ac_freq[sym]) << 8)
    ac[0x00] = 1 + (int(ac_freq[0x00]) << 8)   # EOB
    ac[0xF0] = 1 + (int(ac_freq[0xF0]) << 8)   # ZRL
    dc_bits, dc_vals = build_huffman_table(dc)
    dc_code, dc_len = _codes_from_table(dc_bits, dc_vals)
    # HARD CONSTRAINT from the device packer (ops/jpegenc.huffman_pack):
    # up to three ZRL codes fold into ONE 32-bit deposit, so ZRL's code
    # must stay <= 10 bits (3 x 10 = 30).  Content where runs are rare
    # would otherwise push ZRL to the long-code end and silently corrupt
    # the packed stream; bump its frequency until the bound holds (the
    # cost — a shorter-than-optimal code for a then-rare symbol — is
    # noise).
    for _ in range(32):
        ac_bits, ac_vals = build_huffman_table(ac)
        ac_code, ac_len = _codes_from_table(ac_bits, ac_vals)
        if int(ac_len[0xF0]) <= 10:
            break
        ac[0xF0] = max(ac[0xF0] * 4, 16)
    else:                               # pragma: no cover - 4^32 floor
        raise AssertionError("ZRL code would not converge to <= 10 bits")
    return (dc_bits, dc_vals, dc_code, dc_len,
            ac_bits, ac_vals, ac_code, ac_len)


@functools.lru_cache(maxsize=64)
def _spec_header_cached(width: int, height: int, quality: int,
                        dht_key: bytes) -> bytes:
    """Header assembly memo: the DHT payloads (already serialized into
    ``dht_key`` as the cache key) drop straight in after the frame
    markers — per-tile reassembly on the hot path would be dead
    weight, same reason :func:`fixed_header_bytes` caches."""
    out = bytearray(_frame_markers(width, height, quality))
    out += dht_key
    out += _marker(0xDA, bytes([3, 1, 0x00, 2, 0x00, 3, 0x00, 0, 63, 0]))
    return bytes(out)


def spec_header_bytes(width: int, height: int, quality: int,
                      spec) -> bytes:
    """Full header for an arbitrary shared-table spec (the 8-tuple
    shape of :func:`fixed_huffman_spec`): SOI..SOF0 + DHTs + SOS."""
    dc_bits, dc_vals, _, _, ac_bits, ac_vals, _, _ = spec
    dht = (_marker(0xC4, _dht_payload(0, 0, dc_bits, dc_vals))
           + _marker(0xC4, _dht_payload(1, 0, ac_bits, ac_vals)))
    return _spec_header_cached(width, height, quality, dht)


def finish_stream_with_spec(words: np.ndarray, total_bits: int,
                            width: int, height: int, quality: int,
                            spec) -> bytes:
    """:func:`finish_fixed_stream` for a tuned shared-table spec: the
    device packed the stream with ``spec``'s code/len arrays, so the
    header must declare the same tables."""
    return (spec_header_bytes(width, height, quality, spec)
            + _entropy_bytes(words, total_bits) + b"\xff\xd9")


def _entropy_bytes(words: np.ndarray, total_bits: int) -> bytes:
    """Device-packed u32 words -> stuffed entropy segment bytes (the
    ONE implementation of truncate + 1-pad + 0xFF-stuff, shared by the
    fixed and tuned framings)."""
    n_bytes = (int(total_bits) + 7) // 8
    data = bytearray(np.ascontiguousarray(words).astype("<u4").byteswap()
                     .tobytes()[:n_bytes])
    pad = n_bytes * 8 - int(total_bits)
    if n_bytes:
        data[-1] |= (1 << pad) - 1
    return bytes(data).replace(b"\xff", b"\xff\x00")


def symbol_frequencies(y: np.ndarray, cb: np.ndarray, cr: np.ndarray):
    """(dc_freq, ac_freq) over one tile's zigzag coefficient blocks —
    the measurement feeding :func:`tuned_huffman_spec` (all three
    components share one DC and one AC table, as the device packer
    codes them)."""
    dc = np.zeros(256, dtype=np.int64)
    ac = np.zeros(256, dtype=np.int64)
    for comp in (y, cb, cr):
        _, dcf, acf = _component_symbols(list(comp))
        dc += dcf
        ac += acf
    return dc, ac


def _codes_from_table(bits: np.ndarray, huffval: np.ndarray):
    """Canonical code assignment -> (code[symbol], length[symbol])."""
    code_of = np.zeros(256, dtype=np.uint32)
    len_of = np.zeros(256, dtype=np.int32)
    code = 0
    k = 0
    for length in range(1, 17):
        for _ in range(int(bits[length])):
            code_of[huffval[k]] = code
            len_of[huffval[k]] = length
            code += 1
            k += 1
        code <<= 1
    return code_of, len_of


# ------------------------------------------------------- symbol stream

def _category(v: int) -> int:
    return int(v).bit_length() if v > 0 else int(-v).bit_length()


def _mcu_block_indices(h16: int, w16: int):
    """Per-MCU raster-order block index lists (y_blocks, chroma_index).

    Derived from the single source of scan-order truth,
    :func:`.ops.jpegenc._mcu_scan_index` (the device bit-packer's map), so
    the two Python encoders cannot drift apart.
    """
    from .ops.jpegenc import _mcu_scan_index
    nb_y = h16 * w16 * 4
    scan = _mcu_scan_index(h16, w16)
    return [(row[:4].tolist(), int(row[4]) - nb_y) for row in scan]


def _block_symbols(block: np.ndarray, pred: int):
    """One zigzagged block -> (dc_symbol, dc_val, [(ac_symbol, val)...])."""
    dc_diff = int(block[0]) - pred
    acs = []
    run = 0
    nz = np.nonzero(block[1:])[0]
    last = -1
    for idx in nz:
        run = int(idx) - last - 1
        last = int(idx)
        while run >= 16:
            acs.append((0xF0, 0))
            run -= 16
        v = int(block[1 + idx])
        acs.append(((run << 4) | _category(v), v))
    if last != 62:
        acs.append((0x00, 0))  # EOB
    return _category(dc_diff), dc_diff, acs


class _BitWriter:
    def __init__(self):
        self.out = bytearray()
        self._acc = 0
        self._nbits = 0

    def put(self, code: int, length: int) -> None:
        if length == 0:
            return
        self._acc = (self._acc << length) | (code & ((1 << length) - 1))
        self._nbits += length
        while self._nbits >= 8:
            self._nbits -= 8
            byte = (self._acc >> self._nbits) & 0xFF
            self.out.append(byte)
            if byte == 0xFF:
                self.out.append(0x00)  # byte stuffing
        self._acc &= (1 << self._nbits) - 1

    def flush(self) -> None:
        if self._nbits:
            pad = 8 - self._nbits
            self.put((1 << pad) - 1, pad)


def _amplitude_bits(v: int, size: int) -> int:
    return v if v >= 0 else v + (1 << size) - 1


# ------------------------------------------------------- the encoder

def _component_symbols(blocks: Sequence[np.ndarray]):
    """Scan-ordered blocks -> per-block symbol records + freq tables."""
    dc_freq = np.zeros(256, dtype=np.int64)
    ac_freq = np.zeros(256, dtype=np.int64)
    records = []
    pred = 0
    for block in blocks:
        dc_sym, dc_val, acs = _block_symbols(block, pred)
        pred = int(block[0])
        dc_freq[dc_sym] += 1
        for sym, _ in acs:
            ac_freq[sym] += 1
        records.append((dc_sym, dc_val, acs))
    return records, dc_freq, ac_freq


def _marker(tag: int, payload: bytes) -> bytes:
    return bytes([0xFF, tag]) + (len(payload) + 2).to_bytes(2, "big") + payload


def _dht_payload(cls: int, ident: int, bits: np.ndarray,
                 huffval: np.ndarray) -> bytes:
    return (bytes([(cls << 4) | ident])
            + bytes(int(bits[i]) for i in range(1, 17))
            + huffval.tobytes())


def _frame_markers(width: int, height: int, quality: int) -> bytes:
    """SOI through SOF0 (everything before the Huffman tables)."""
    qy, qc = quant_tables(quality)
    zig = zigzag_order()
    out = bytearray()
    out += b"\xff\xd8"  # SOI
    out += _marker(0xE0, b"JFIF\x00\x01\x01\x00\x00\x01\x00\x01\x00\x00")
    out += _marker(0xDB, bytes([0]) + qy.reshape(-1)[zig].tobytes())
    out += _marker(0xDB, bytes([1]) + qc.reshape(-1)[zig].tobytes())
    out += _marker(0xC0, bytes([8])                       # SOF0: baseline
                   + height.to_bytes(2, "big") + width.to_bytes(2, "big")
                   + bytes([3,
                            1, 0x22, 0,     # Y: 2x2 sampling, qtable 0
                            2, 0x11, 1,     # Cb: 1x1, qtable 1
                            3, 0x11, 1]))   # Cr
    return bytes(out)


@functools.lru_cache(maxsize=64)
def fixed_header_bytes(width: int, height: int, quality: int) -> bytes:
    """Full fixed-table header: SOI..SOF0 + shared DHTs + SOS.

    The device bit-packer's stream drops straight in after this; all three
    components reference DC/AC table 0.
    """
    dc_bits, dc_vals, _, _, ac_bits, ac_vals, _, _ = fixed_huffman_spec()
    out = bytearray(_frame_markers(width, height, quality))
    out += _marker(0xC4, _dht_payload(0, 0, dc_bits, dc_vals))
    out += _marker(0xC4, _dht_payload(1, 0, ac_bits, ac_vals))
    out += _marker(0xDA, bytes([3, 1, 0x00, 2, 0x00, 3, 0x00, 0, 63, 0]))
    return bytes(out)


def finish_fixed_stream(words: np.ndarray, total_bits: int,
                        width: int, height: int,
                        quality: int = 85) -> bytes:
    """Wrap a device-packed bitstream into a complete JFIF file.

    ``words`` is the u32 array from the device packer, stream bit 0 at the
    MSB of word 0.  Host work is O(stream bytes): big-endian byte view,
    truncate to ``total_bits``, 1-pad the final byte, 0xFF byte-stuffing,
    header + EOI framing (the same :func:`_entropy_bytes` the tuned
    framing uses).
    """
    return (fixed_header_bytes(width, height, quality)
            + _entropy_bytes(words, total_bits) + b"\xff\xd9")


def encode_jfif(y: np.ndarray, cb: np.ndarray, cr: np.ndarray,
                width: int, height: int, quality: int = 85,
                huffman: str = "optimal") -> bytes:
    """Entropy-encode one image's coefficient blocks into a JFIF stream.

    ``width``/``height`` are the true (pre-MCU-padding) dimensions written
    into SOF0; the coefficient arrays cover the padded 16-aligned frame.
    ``huffman="fixed"`` uses the shared :func:`fixed_huffman_spec` tables
    (one pass, the device packer's mode — byte-parity reference for it)
    instead of per-image optimal tables.
    """
    h16 = (height + 15) // 16
    w16 = (width + 15) // 16
    if y.shape[0] != h16 * w16 * 4 or cb.shape[0] != h16 * w16:
        raise ValueError(
            f"coefficient block counts {y.shape[0]}/{cb.shape[0]} do not "
            f"match a {w16}x{h16}-MCU frame"
        )
    mcus = _mcu_block_indices(h16, w16)
    y_scan = [y[i] for m in mcus for i in m[0]]
    cb_scan = [cb[m[1]] for m in mcus]
    cr_scan = [cr[m[1]] for m in mcus]

    y_rec, y_dcf, y_acf = _component_symbols(y_scan)
    cb_rec, c_dcf, c_acf = _component_symbols(cb_scan)
    cr_rec, c_dcf2, c_acf2 = _component_symbols(cr_scan)
    c_dcf += c_dcf2
    c_acf += c_acf2

    if huffman == "fixed":
        dc_bits, dc_vals, dc_code, dc_len, ac_bits, ac_vals, ac_code, \
            ac_len = fixed_huffman_spec()
        shared = {"dc": (dc_code, dc_len), "ac": (ac_code, ac_len)}
        codes = {(kind, t): shared[kind]
                 for kind in ("dc", "ac") for t in (0, 1)}
        out = bytearray(fixed_header_bytes(width, height, quality))
    else:
        tables = {
            ("dc", 0): build_huffman_table(y_dcf),
            ("ac", 0): build_huffman_table(y_acf),
            ("dc", 1): build_huffman_table(c_dcf),
            ("ac", 1): build_huffman_table(c_acf),
        }
        codes = {k: _codes_from_table(*v) for k, v in tables.items()}
        out = bytearray(_frame_markers(width, height, quality))
        out += _marker(0xC4, _dht_payload(0, 0, *tables[("dc", 0)]))
        out += _marker(0xC4, _dht_payload(1, 0, *tables[("ac", 0)]))
        out += _marker(0xC4, _dht_payload(0, 1, *tables[("dc", 1)]))
        out += _marker(0xC4, _dht_payload(1, 1, *tables[("ac", 1)]))
        out += _marker(0xDA, bytes([3, 1, 0x00, 2, 0x11, 3, 0x11, 0, 63, 0]))

    w = _BitWriter()

    def put_block(rec, dc_codes, ac_codes):
        dc_sym, dc_val, acs = rec
        c, l = dc_codes
        w.put(int(c[dc_sym]), int(l[dc_sym]))
        if dc_sym:
            w.put(_amplitude_bits(dc_val, dc_sym), dc_sym)
        c, l = ac_codes
        for sym, v in acs:
            w.put(int(c[sym]), int(l[sym]))
            size = sym & 0x0F
            if size:
                w.put(_amplitude_bits(v, size), size)

    yi = iter(y_rec)
    cbi = iter(cb_rec)
    cri = iter(cr_rec)
    for _ in mcus:
        for _ in range(4):
            put_block(next(yi), codes[("dc", 0)], codes[("ac", 0)])
        put_block(next(cbi), codes[("dc", 1)], codes[("ac", 1)])
        put_block(next(cri), codes[("dc", 1)], codes[("ac", 1)])
    w.flush()
    out += w.out
    out += b"\xff\xd9"  # EOI
    return bytes(out)
